"""Continuous-batching serving engine over AOT-compiled bucket shapes.

The scheduler half of the serving lane (``serve.decode`` is the program
half).  Design constraints, in order:

1. **Zero lowering after warmup.**  Every shape the engine can ever run
   — one prefill program per prompt-length bucket, one decode program
   per batch bucket, one classify program per batch bucket — is
   AOT-compiled at construction through ``obs.efficiency.aot_compile``
   (the ``StepFlopsProbe`` lowering path, so ``--compile_cache`` warms
   them across runs).  After warmup the engine only calls AOT
   executables: an off-ladder shape *raises* instead of recompiling,
   and the ``serve-bucket-recompile`` analysis lint guards the source
   so no jit/lower call site creeps into the traffic path.  Measured
   the same way as the round-10 hit/miss banner: compile-cache entry
   deltas, re-counted after traffic (``post_warmup_compiles``).
2. **Continuous batching** (Orca): admission and retirement happen per
   decode step.  A newly arrived request is prefilled as soon as a
   slot and pages are free, joins the running batch at the next step,
   and retires the step it hits its output budget — short requests are
   never held hostage to long batchmates.  ``--batching=static`` is
   the classic control arm: collect a full batch, run it to
   completion, only then admit again.
3. **Paged KV cache** (vLLM): requests hold page tables into one
   shared pool, not max-length slabs.  Allocation is conservative —
   a request's worst-case page count is reserved at admission — and
   under ``--kv_preempt=on`` a starved admit preempts the resident
   with the most pages per token of progress, frees its pages, and
   requeues it carrying its generated prefix: re-admission re-prefills
   prompt+prefix, so no token is lost across residencies (round 23;
   the admission half of the ROADMAP on-demand-paging item).
4. **Graceful degradation** (round 23): deadline-aware load shedding
   (``--shed`` against ``--deadline_ms``), per-request quarantine of
   non-finite logits, a SIGTERM drain that journals every unfinished
   request for ``--serve_resume``, and a scheduler-iteration watchdog
   (``--serve_step_timeout_s``) — overload and faults degrade the
   answer set, never the process.  Every knob defaults off, and the
   off path adds no host transfers: the determinism and zero-lowering
   pins ride on an unarmed ``run()`` staying byte-identical.

Timing goes through an injectable clock so tests drive the closed
loop in virtual time (``VirtualClock``): real runs measure wall
seconds, virtual runs charge a deterministic modeled cost per step
kind and make ``sleep`` instant — same scheduler code path either way.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
from typing import Any, Callable

import numpy as np

from tpu_hc_bench.flags import BenchmarkConfig, parse_serve_buckets
from tpu_hc_bench.obs import efficiency as obs_efficiency
from tpu_hc_bench.obs import kv as kv_mod
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import requests as requests_mod
from tpu_hc_bench.obs import timeline as timeline_mod
from tpu_hc_bench.obs import signals as signals_mod
from tpu_hc_bench.obs import sketch as sketch_mod
from tpu_hc_bench.resilience import preempt as preempt_mod
from tpu_hc_bench.resilience import watchdog as watchdog_mod
from tpu_hc_bench.serve import faults as faults_mod
from tpu_hc_bench.serve import slo as slo_mod
from tpu_hc_bench.serve.arrivals import Request

# serve records land every this-many engine steps — frequent enough for
# `obs watch` to show a live queue, rare enough to stay O(run)/stream
_SERVE_RECORD_EVERY = 16

# round 24: the retained-request-record cap.  Percentiles stream
# through the mergeable sketch (exact over the whole run, bounded
# buckets); the raw record ring only feeds the folds that genuinely
# need per-request rows (tail attribution, burn-rate windows, the KV
# honesty gap), which degrade gracefully to the freshest N under a
# week-long serve instead of growing without bound.
_DONE_SAMPLE_CAP = 4096


def ceil_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def pick_bucket(ladder: tuple[int, ...], n: int) -> int:
    """Smallest bucket >= n (admission control guarantees one exists)."""
    for b in ladder:
        if b >= n:
            return b
    raise ValueError(f"no bucket >= {n} in ladder {ladder} — admission "
                     f"control should have clamped this")


class PageAllocator:
    """Refcounted free-list allocator over the KV page pool; page 0 is
    the reserved trash page (padded/inactive rows read and write it)
    and is never handed out.

    Round 25 makes pages a SHARED resource: a physical page can be
    held by several requests (a prefix-cache hit) and by the cache
    itself, so every holder takes a reference (``alloc``/``share``)
    and drops it through ``free`` — a page returns to the free list
    only when its last holder lets go.  All page-table stores and
    free-list motion live inside this class (``bind`` is the one
    sanctioned table store); the ``page-refcount-discipline`` lint
    pins that invariant at the source level, because a bare
    ``free_list.append`` beside a nonzero refcount is exactly the
    silent-corruption class COW introduces.

    Counter semantics (the r22 ``obs timeline`` counter track reads
    these, so they must stay honest):

    - ``recycled`` counts a page handed out again by ``alloc`` after a
      genuine free — the pool-churn signal a leak (pages freed but
      never reused) hides.
    - ``cow_copies`` counts copy-on-write page duplications
      (``cow_alloc``).  A COW is NOT a recycle: the page it pops was
      already churned through ``alloc``'s account when it last left
      the free list, and folding copies into ``recycled`` would read
      as pool churn when it is sharing traffic.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"KV pool needs >= 2 pages (one is the reserved trash "
                f"page): {num_pages}")
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))
        self.pages_peak = 0
        self.recycled = 0
        self.cow_copies = 0
        self._ever_used = [False] * num_pages
        self._refcount = [0] * num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - 1 - len(self._free)

    def _take(self, count_recycle: bool) -> int:
        p = self._free.pop()
        self._refcount[p] = 1
        if self._ever_used[p]:
            if count_recycle:
                self.recycled += 1
        else:
            self._ever_used[p] = True
        return p

    def alloc(self, n: int) -> list[int] | None:
        if n > len(self._free):
            return None
        out = [self._take(count_recycle=True) for _ in range(n)]
        if self.used_pages > self.pages_peak:
            self.pages_peak = self.used_pages
        return out

    def cow_alloc(self) -> int | None:
        """One page for a copy-on-write duplication: counted under
        ``cow_copies``, never ``recycled`` (see class docstring)."""
        if not self._free:
            return None
        p = self._take(count_recycle=False)
        self.cow_copies += 1
        if self.used_pages > self.pages_peak:
            self.pages_peak = self.used_pages
        return p

    def share(self, pages: list[int]) -> None:
        """One additional reference per page (a prefix-cache hit or
        the cache's own retention hold)."""
        for p in pages:
            assert self._refcount[p] > 0, f"share of unheld page {p}"
            self._refcount[p] += 1

    def refcount(self, page: int) -> int:
        return self._refcount[page]

    def free(self, pages: list[int]) -> None:
        """Drop one reference per page; a page rejoins the free list
        at refcount zero (sole-holder frees behave exactly like the
        pre-r25 allocator)."""
        for p in pages:
            assert self._refcount[p] > 0, f"free of unheld page {p}"
            self._refcount[p] -= 1
            if self._refcount[p] == 0:
                self._free.append(p)

    def bind(self, table: np.ndarray, slot: int, page: int) -> None:
        """The one sanctioned page-table store: point ``table[slot]``
        at a page this allocator has handed out and still tracks."""
        assert self._refcount[page] > 0, f"bind of unheld page {page}"
        table[slot] = page


class KVLedger:
    """Round 22 (obs.kv): the KV-pool utilization ledger — pages
    reserved by admission vs pages actually written, integrated over
    step wall into the page-seconds behind ``kv_pool_util``.

    Writer-side bookkeeping, by declared limit: "written" is inferred
    from scheduler state (prompt length at admit, one token per decode
    step), not device introspection — the compiled programs do write
    those slots, but nothing here reads HBM back.  Every update is a
    couple of host int/float ops, pinned under the round-17
    1%-of-step-wall guard by test.
    """

    __slots__ = ("page_size", "reserved_now", "written_now",
                 "reserved_page_s", "written_page_s")

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.reserved_now = 0       # pages held by in-flight requests
        self.written_now = 0        # pages with >= 1 written token
        self.reserved_page_s = 0.0
        self.written_page_s = 0.0

    def admit(self, pages_reserved: int, prompt_len: int) -> None:
        self.reserved_now += pages_reserved
        self.written_now += -(-prompt_len // self.page_size)

    def grow(self, n: int = 1) -> None:
        """Round 25 on-demand growth: pages taken mid-flight extend the
        holder's reservation from the moment they are bound (written
        follows through ``token`` when the boundary token lands)."""
        self.reserved_now += n

    def token(self, length_before: int) -> None:
        # one appended token touches a new page iff the pre-append
        # length sits on a page boundary — O(1) per generated token
        if length_before % self.page_size == 0:
            self.written_now += 1

    def retire(self, pages_reserved: int, length: int) -> int:
        """Release a request's pages; returns its final written-page
        count (== peak under worst-case reservation: lengths only grow
        and pages free only at retirement)."""
        final = -(-length // self.page_size)
        self.reserved_now -= pages_reserved
        self.written_now -= final
        return final

    def charge(self, dt: float) -> None:
        self.reserved_page_s += self.reserved_now * dt
        self.written_page_s += self.written_now * dt


class MonotonicClock:
    """Real time: the closed-loop benchmark clock."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, dt: float) -> None:
        if dt > 0:
            time.sleep(dt)

    def charge(self, kind: str, real_s: float) -> None:
        # real compute already advanced now(); nothing to model
        del kind, real_s


class VirtualClock:
    """Deterministic test clock: ``sleep`` is instant (time jumps) and
    each engine step advances time by ``costs[kind]`` — or by the real
    measured seconds when the kind has no modeled cost, so a cost-free
    VirtualClock still yields compute-shaped (just sleep-free) time."""

    def __init__(self, costs: dict[str, float] | None = None):
        self.t = 0.0
        self.costs = dict(costs or {})

    def now(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += max(0.0, dt)

    def charge(self, kind: str, real_s: float) -> None:
        self.t += self.costs.get(kind, real_s)


@dataclasses.dataclass
class _InFlight:
    """Host-side bookkeeping for one admitted request."""

    req: Request
    pages: list[int]
    table: np.ndarray               # int32 [table_width]
    length: int = 0                 # tokens in KV cache
    produced: int = 0               # generated tokens (prefill's counts)
    last_token: int = 0
    t_admit: float = 0.0
    t_first: float | None = None
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    # request-attribution bookkeeping (round 20, obs.requests): summed
    # wall of the decode/classify steps this request was resident for,
    # and the end instant of its last such step — two float stores per
    # resident per step, well under the round-17 recorder guard
    active_s: float = 0.0
    t_last: float | None = None
    # round 23 (KV-pressure preemption): completed residencies, and
    # tokens produced in THIS residency — a re-admitted victim must
    # earn one decode token before it is preemptible again, which is
    # the whole livelock-freedom argument (every residency advances
    # the request by >= 1 token)
    preempts: int = 0
    produced_res: int = 0
    # round 25 (lazy reservation + prefix sharing): pages grown on
    # demand after admission, and page slots admitted pointing at
    # shared prefix-cache pages — the footprint record stamps both
    pages_grown: int = 0
    prefix_shared: int = 0


class ServeEngine:
    """One model's serving engine: compiled buckets + scheduler.

    Construction compiles every bucket (the warmup); ``run`` plays a
    request trace through either batching arm.  One engine instance
    serves any number of runs — arms share the warmed executables, so
    the A/B never pays a second compile.
    """

    def __init__(self, cfg: BenchmarkConfig,
                 print_fn: Callable[[str], None] = print):
        import jax
        import jax.numpy as jnp

        from tpu_hc_bench.models import get_model_spec, create_model
        from tpu_hc_bench.train.driver import (
            _cache_entry_count, _resolve_compile_cache)

        if cfg.workload != "serve":
            raise ValueError(
                "ServeEngine needs a workload='serve' config (use "
                "flags.parse_flags(argv, workload='serve') or set the "
                "field before resolve())")
        self.cfg = cfg
        self.print_fn = print_fn
        self._jnp = jnp

        # persistent compile cache first, so the warmup compiles hit or
        # populate it (the round-10 mechanism, reused verbatim)
        self.cache_dir = _resolve_compile_cache(cfg, print_fn)
        self._count_cache = (
            (lambda: _cache_entry_count(self.cache_dir))
            if self.cache_dir else (lambda: 0))
        entries_before = self._count_cache()

        spec = get_model_spec(cfg.model)
        if spec.is_text and not spec.causal_lm:
            raise ValueError(
                f"--model {cfg.model}: MLM members have no "
                "autoregressive serving story; serve a decoder family "
                "(gpt2*/moe*/llama*) or a classify member")
        self.decode_mode = bool(spec.causal_lm)
        self.max_ctx = cfg.max_prompt_len + cfg.max_output_len
        # decode-kernel/quant arms (round 18) are decode-lane knobs;
        # a classify member accepting them would be the silent-no-op
        # flag the lane contract forbids
        self.decode_attention = cfg.decode_attention
        self.quant = cfg.quant
        self.block_pages = cfg.decode_block_pages or 1
        if not self.decode_mode and (
                cfg.decode_attention != "gather" or cfg.quant != "off"
                or cfg.decode_block_pages):
            raise ValueError(
                f"--model {cfg.model} serves single-forward classify "
                "requests; --decode_attention/--quant/"
                "--decode_block_pages shape the paged decode step and "
                "have no meaning here")
        if not self.decode_mode and (
                cfg.kv_reserve != "worst" or cfg.prefix_cache != "off"):
            raise ValueError(
                f"--model {cfg.model} serves single-forward classify "
                "requests with no KV pool; --kv_reserve/--prefix_cache "
                "shape paged-decode admission and have no meaning here")

        dtype = jnp.dtype(cfg.compute_dtype)
        if self.decode_mode:
            self.model, self.spec = create_model(
                cfg.model, dtype=dtype, seq_len=self.max_ctx)
        else:
            self.model, self.spec = create_model(
                cfg.model, num_classes=cfg.num_classes, dtype=dtype)

        rng = jax.random.PRNGKey(cfg.seed)
        if self.decode_mode:
            example = jnp.zeros((1, min(8, self.max_ctx)), jnp.int32)
        else:
            example = jnp.zeros((1,) + tuple(self.spec.input_shape),
                                jnp.float32)
        self.variables = self.model.init(rng, example, train=False)
        self.params = self.variables.get("params", self.variables)

        # --- bucket ladders + KV pool geometry ---
        self.batch_buckets = parse_serve_buckets(cfg.serve_buckets,
                                                 cfg.max_in_flight)
        self.cap = min(cfg.max_in_flight, max(self.batch_buckets))
        if self.cap < cfg.max_in_flight:
            print_fn(f"serve: max_in_flight clamped to the top decode "
                     f"bucket: {cfg.max_in_flight} -> {self.cap}")
        ladder = []
        s = min(8, ceil_pow2(cfg.max_prompt_len))
        while s < cfg.max_prompt_len:
            ladder.append(s)
            s *= 2
        # the top bucket never exceeds max_ctx: the models' position
        # tables are max_ctx rows, and an oversized bucket would both
        # compile a wider program than any request needs and rely on
        # XLA's out-of-bounds gather clamping for the pad positions
        ladder.append(min(s, self.max_ctx))
        self.prefill_buckets = tuple(ladder)
        self.page_size = cfg.kv_page_size
        self.table_width = -(-self.max_ctx // self.page_size)
        self.num_pages = cfg.kv_pages or (1 + self.cap * self.table_width)
        if self.decode_mode and self.num_pages < 1 + self.table_width:
            # classify members never allocate the pool, so an explicit
            # --kv_pages must not crash their (KV-free) construction
            raise ValueError(
                f"--kv_pages={cfg.kv_pages} cannot hold even one request "
                f"(need {1 + self.table_width}: a trash page + "
                f"{self.table_width} pages of {self.page_size} tokens "
                f"for prompt+output {self.max_ctx})")

        # --- warmup: AOT-compile every bucket ---
        self.compiled: dict[tuple[str, int], Any] = {}
        self.lower_count = 0
        # pool geometry bytes (round 22: the serve summary renders the
        # configured pool beside the utilization line) — measured off
        # the actual device arrays at warmup, None for classify members
        self.kv_pool_bytes: int | None = None
        self.kv_scale_bytes = 0
        t0 = time.perf_counter()
        if self.decode_mode:
            self._warm_decode()
        else:
            self._warm_classify()
        warm_s = time.perf_counter() - t0
        self.entries_after_warmup = self._count_cache()
        self.compile_record = {
            "buckets": len(self.compiled),
            "warmup_s": round(warm_s, 3),
            "cache_dir": self.cache_dir,
            "entries_before": entries_before,
            "entries_after_warmup": self.entries_after_warmup,
            "new_entries": self.entries_after_warmup - entries_before,
            "warm": (self.entries_after_warmup == entries_before
                     and entries_before > 0),
            "decode_attention": (self.decode_attention
                                 if self.decode_mode else None),
            "quant": self.quant,
            # block pages only exist on the paged arm: reporting the
            # coerced 1 under gather would render a knob resolve()
            # itself rejects there
            "decode_block_pages": (
                self.block_pages if self.decode_mode
                and self.decode_attention == "paged" else None),
        }
        if self.decode_mode:
            _, worst_decode = self.aot_memory_worst(kinds=("decode",))
            self.compile_record["aot_decode_temp_bytes"] = (
                worst_decode.get("temp_bytes") if worst_decode else None)
            arm = (f"serve decode arm: attention={self.decode_attention} "
                   f"quant={self.quant}")
            if self.decode_attention == "paged":
                arm += f" block_pages={self.block_pages}"
            tb = self.compile_record["aot_decode_temp_bytes"]
            if tb is not None:
                arm += (f"; worst decode bucket AOT temp "
                        f"{tb / 2**20:.1f} MiB")
            print_fn(arm)
        kinds = collections.Counter(k for k, _ in self.compiled)
        print_fn(
            "serve warmup: "
            + ", ".join(f"{n} {k} bucket(s)" for k, n in sorted(
                kinds.items()))
            + f" AOT-compiled in {warm_s:.1f}s"
            + (f"; compile cache: "
               f"{self.compile_record['new_entries']} new entr"
               f"{'y' if self.compile_record['new_entries'] == 1 else 'ies'}"
               f" ({'warm start' if self.compile_record['warm'] else 'cold/partial'})"
               if self.cache_dir else ""))
        self._check_hbm_budget(print_fn)

    def aot_memory_worst(self, kinds=None) -> tuple:
        """``(bucket key, memory_analysis dict)`` of the warmed
        ladder's worst bucket by AOT total bytes, optionally limited to
        the given program kinds (``("decode",)`` isolates the decode
        arm the kernel A/B moves) — ``(None, None)`` where the backend
        exposes no analysis."""
        from tpu_hc_bench.obs import memory as obs_memory

        worst, worst_key = None, None
        for key, compiled in self.compiled.items():
            if kinds is not None and key[0] not in kinds:
                continue
            ma = obs_memory.memory_analysis_of_compiled(compiled)
            if ma and (worst is None
                       or ma["total_bytes"] > worst["total_bytes"]):
                worst, worst_key = ma, key
        return worst_key, worst

    def _check_hbm_budget(self, print_fn) -> None:
        """``--hbm_budget`` in the serving lane: the warmed ladder's
        worst bucket (by AOT ``memory_analysis`` total — arguments
        include the params and the whole KV pool) against the budget,
        verdict printed BEFORE traffic.  A shared flag that parsed but
        never checked anything would be the silent-no-op knob the lane
        contract forbids."""
        if self.cfg.hbm_budget is None:
            return
        from tpu_hc_bench.obs import memory as obs_memory

        budget_bytes, note = obs_memory.resolve_hbm_budget_bytes(
            obs_memory.parse_hbm_budget(self.cfg.hbm_budget))
        worst_key, worst = self.aot_memory_worst()
        for ln in obs_memory.budget_lines(
                worst, budget_bytes, note,
                advice="shrink --serve_buckets/--max_in_flight, "
                       "--kv_pages, or --max_prompt_len/--max_output_len"):
            print_fn(ln + (f" [worst bucket: {worst_key[0]} "
                           f"{worst_key[1]}]"
                           if worst_key and budget_bytes else ""))
        self.compile_record["hbm_budget"] = {
            "budget_bytes": budget_bytes,
            "worst_bucket": list(worst_key) if worst_key else None,
            "memory_analysis": worst,
        }

    # -- warmup namespace: the ONLY place that may lower/compile --------

    def _aot(self, key: tuple[str, int], fn, *example, donate=()):
        import jax

        if jax.default_backend() == "cpu":
            donate = ()             # CPU backend: donation unimplemented,
                                    # avoid the per-compile warning
        jitted = jax.jit(fn, donate_argnums=donate)
        self.lower_count += 1
        self.compiled[key] = obs_efficiency.aot_compile(jitted, *example)

    def _warm_decode(self) -> None:
        from tpu_hc_bench.serve import decode as decode_mod

        jnp = self._jnp
        self.family = decode_mod.build_family(self.model,
                                              quant=self.quant)
        # int8_w: the decode programs read the quantized tree; the
        # original f32 params stay on self.params (parity tests read
        # them for the full-forward reference)
        self.exec_params = (
            decode_mod.quantize_weights(self.family, self.params)
            if self.quant == "int8_w" else self.params)
        self._kv = decode_mod.init_kv_state(
            self.family, self.num_pages, self.page_size,
            jnp.dtype(self.cfg.compute_dtype), quant=self.quant)
        import jax

        leaves = jax.tree_util.tree_leaves(self._kv)
        self.kv_pool_bytes = int(sum(x.nbytes for x in leaves))
        if self.quant == "int8_kv":
            # the per-(layer, page) f32 scale planes ride the pool
            # bytes — int8 pages without their scales would undercount
            self.kv_scale_bytes = int(sum(
                x.nbytes for x in leaves if x.dtype == jnp.float32))
        w = self.table_width
        for s in self.prefill_buckets:
            fn = decode_mod.build_prefill_fn(
                self.family, self.page_size, w, quant=self.quant)
            self._aot(("prefill", s), fn, self.exec_params, self._kv,
                      np.zeros((1, s), np.int32), np.int32(1),
                      np.zeros((w,), np.int32), donate=(1,))
        for b in self.batch_buckets:
            fn = decode_mod.build_decode_fn(
                self.family, self.page_size, w,
                attention=self.decode_attention, quant=self.quant,
                block_pages=self.block_pages)
            self._aot(("decode", b), fn, self.exec_params, self._kv,
                      np.zeros((b,), np.int32), np.zeros((b, w), np.int32),
                      np.zeros((b,), np.int32), np.zeros((b,), bool),
                      donate=(1,))
        # round 25: the one COW program — page-count-shaped, not
        # bucket-shaped, so a single warmup covers every copy the
        # prefix cache can ever trigger (zero lowering after warmup)
        self._aot(("page_copy", 0), decode_mod.build_page_copy_fn(),
                  self._kv, np.int32(0), np.int32(0), donate=(0,))

    def _warm_classify(self) -> None:
        model = self.model

        def classify(variables, x):
            return self._jnp.argmax(
                model.apply(variables, x, train=False), axis=-1)

        shape = tuple(self.spec.input_shape)
        for b in self.batch_buckets:
            self._aot(("classify", b), classify, self.variables,
                      np.zeros((b,) + shape, np.float32))

    # -- traffic path: AOT executables only -----------------------------

    def _timed(self, clock, kind: str, fn):
        import jax

        c0 = clock.now()
        m0 = time.monotonic()
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        clock.charge(kind, time.perf_counter() - t0)
        # flight recorder (obs.timeline): every engine step kind
        # (prefill/decode/classify) lands as a span — the serving lane's
        # always-on host timeline, real wall even under a VirtualClock
        timeline_mod.record_span(kind, m0, time.monotonic())
        return out, clock.now() - c0

    def _classify_input(self, req: Request) -> np.ndarray:
        rng = np.random.default_rng((self.cfg.seed, 13, req.rid))
        return rng.standard_normal(
            tuple(self.spec.input_shape)).astype(np.float32)

    def run(self, requests: list[Request], batching: str | None = None,
            writer: obs_metrics.MetricsWriter | None = None,
            clock=None, fleet=None, *, faults=None, shed=None,
            deadline_ms=None, kv_preempt=None, kv_reserve=None,
            prefix_cache=None, journal_path=None,
            drain_handler=None, step_timeout_s=None,
            on_watchdog=None) -> dict:
        """Play a request trace; returns the serve summary record.

        Deterministic given (engine seed, trace, clock): greedy decode,
        counter-keyed synthesis, and arrival-ordered admission leave no
        hidden state between runs — arms share one warmed engine.

        ``fleet`` is an optional ``obs.fleet.FleetWriter``: when given
        (``serve/cli.run_serve`` wires one on metrics runs) the engine
        heartbeats at the serve-record cadence with the pool high-water
        under ``kv_peak_pages``, so ``obs watch``'s fleet view shows
        per-host KV pressure the same way it shows ``mem_peak_bytes``.

        The keyword-only degradation knobs (round 23) override their
        config twins per run, so tests and the faults A/B drive policy
        arms through ONE warmed engine — a second warmup per arm would
        break the zero-compile contract.  A ``faults`` plan is
        consumed as it fires (one-shot hooks): pass a fresh
        ``faults.parse_serve_plan`` result per run.  ``drain_handler``
        replaces the engine's own SIGTERM/SIGINT handler (tests poll a
        fake); ``on_watchdog`` replaces the watchdog's ``os._exit``.
        """
        batching = batching or self.cfg.batching
        if batching not in ("continuous", "static"):
            raise ValueError(f"batching must be continuous|static: "
                             f"{batching!r}")
        if faults is None and self.cfg.serve_faults:
            faults = faults_mod.parse_serve_plan(self.cfg.serve_faults)
        shed = shed if shed is not None else self.cfg.shed
        kv_preempt = (kv_preempt if kv_preempt is not None
                      else self.cfg.kv_preempt)
        # round 25: the reservation/sharing arms override per run like
        # the other policy knobs — the three-arm kv bench drives all of
        # worst / lazy / lazy+prefix through ONE warmed engine
        kv_reserve = (kv_reserve if kv_reserve is not None
                      else self.cfg.kv_reserve)
        prefix_cache = (prefix_cache if prefix_cache is not None
                        else self.cfg.prefix_cache)
        if kv_reserve not in ("worst", "lazy"):
            raise ValueError(
                f"kv_reserve must be worst|lazy: {kv_reserve!r}")
        if prefix_cache not in ("off", "on"):
            raise ValueError(
                f"prefix_cache must be off|on: {prefix_cache!r}")
        if prefix_cache == "on" and kv_reserve != "lazy":
            raise ValueError(
                "prefix_cache=on requires kv_reserve=lazy (sharing "
                "only saves pages when admission stops reserving the "
                "worst case)")
        headroom = self.cfg.kv_growth_headroom
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else (self.cfg.deadline_ms or self.cfg.slo_e2e_ms))
        if shed not in ("off", "admit", "deadline"):
            raise ValueError(f"shed must be off|admit|deadline: {shed!r}")
        if shed != "off" and not deadline_ms:
            raise ValueError(
                "--shed needs a deadline to shed against: set "
                "--deadline_ms (or --slo_e2e_ms, its fallback)")
        deadline_s = (deadline_ms or 0.0) / 1e3
        if not self.decode_mode and (faults or kv_preempt == "on"):
            raise ValueError(
                f"--model {self.cfg.model} serves single-forward "
                "classify requests; --serve_faults/--kv_preempt drive "
                "the paged decode path and have no meaning here")
        if not self.decode_mode and (kv_reserve != "worst"
                                     or prefix_cache != "off"):
            raise ValueError(
                f"--model {self.cfg.model} serves single-forward "
                "classify requests with no KV pool; "
                "--kv_reserve/--prefix_cache have no meaning here")
        # the quarantine guard arms with EITHER policy knob: reading
        # logits back is one host transfer per step that the unarmed
        # lane must not pay (an injected NaN with both knobs off flows
        # through undetected — the faults A/B's control arm)
        guard = shed != "off" or kv_preempt == "on"
        writer = writer or obs_metrics.MetricsWriter(None)
        # flight recorder: honor --flight_recorder and, on metrics runs,
        # persist this process's spans beside the stream
        timeline_mod.configure(
            enabled=self.cfg.flight_recorder != "off",
            run_dir=getattr(writer, "out_dir", None))
        clock = clock or MonotonicClock()
        allocator = PageAllocator(self.num_pages) if self.decode_mode \
            else None
        ledger = KVLedger(self.page_size) if self.decode_mode else None
        # round 25: the shared-prefix cache lives per run (it holds
        # references into THIS run's allocator) and its counters feed
        # prefix_hit_frac on the kv_pool record cadence
        cache = None
        if self.decode_mode and prefix_cache == "on":
            from tpu_hc_bench.serve import prefix_cache as prefix_mod

            cache = prefix_mod.PrefixCache(allocator, self.page_size)
        pages_grown_total = 0
        prefix_hits = 0
        prefix_lookups = 0
        prefix_shared_total = 0
        # queue-wait cause split (round 22): rid -> accumulated seconds
        # blocked on [pool_starved, batch_full] while sitting in queue
        wait_causes: dict[int, list[float]] = {}
        # round 23 degradation state: terminal dispositions counted by
        # cause, the preempted-victim carry (rid -> prefix + original
        # lifecycle instants, so the conserved components span both
        # residencies), and the admit-to-done EWMA the predictive shed
        # judges against
        degrade: dict = {"shed": {}, "preempts": 0, "requeues": 0,
                         "quarantined": 0}
        carry: dict[int, dict] = {}
        finished = 0
        service_ewma_s: float | None = None
        squeezed_seen = 0
        drained: dict | None = None
        pending = sorted(requests, key=lambda r: (r.arrival_s, r.rid))
        n = len(pending)
        if self.decode_mode:
            over = [r for r in pending
                    if r.prompt_len > self.cfg.max_prompt_len
                    or r.output_len > self.cfg.max_output_len]
            if over:
                raise ValueError(
                    f"{len(over)} request(s) exceed the compiled ladder "
                    f"(prompt<={self.cfg.max_prompt_len}, "
                    f"output<={self.cfg.max_output_len}); request "
                    f"{over[0].rid} is {over[0].prompt_len}/"
                    f"{over[0].output_len} — shapes outside the warmed "
                    "buckets never run")
            kv = self._kv
        queue: collections.deque[Request] = collections.deque()
        active: list[_InFlight] = []
        # bounded retention (round 24): the freshest N raw records; the
        # sketches below carry the run-lifetime percentiles
        done: collections.deque[dict] = collections.deque(
            maxlen=_DONE_SAMPLE_CAP)
        completed_ok = 0
        run_sk = {f: sketch_mod.QuantileSketch()
                  for f in slo_mod.LATENCY_FIELDS}
        win_sk = {f: sketch_mod.QuantileSketch()
                  for f in slo_mod.LATENCY_FIELDS}
        win_idx = 0
        win_t0 = 0.0
        last_productive = 0.0
        win_stats: dict = {"n": 0, "viol": 0, "blocked": [0.0, 0.0]}
        # live health signals (round 24): hysteresis-gated judgments
        # per record window, appended to signals.jsonl beside the
        # stream; the e2e target is the deadline (or SLO) when set —
        # without one the overload measure is "no evidence", never 0
        sig_engine = signals_mod.SignalEngine()
        sig_target_ms = deadline_ms or self.cfg.slo_e2e_ms or None
        out_dir = getattr(writer, "out_dir", None)
        signals_file = (signals_mod.signals_path(out_dir)
                        if writer.enabled and out_dir else None)
        idx = 0
        steps = {"prefill": 0, "decode": 0, "classify": 0}
        tokens_out = 0
        productive_s = 0.0
        queue_depths: list[int] = []
        # per-(kind,bucket) utilization: key -> [steps, rows, active
        # rows, wall s] — the occupancy heatmap's raw counts
        butil: dict[str, list] = {}
        t0 = clock.now()
        last_record_step = 0
        # the request-lane timeline anchor: engine-relative instants
        # (arrival_s et al.) placed on the wall by `obs timeline`
        writer.event("serve_clock", t_unix=time.time(),
                     t_mono=time.monotonic(), batching=batching)

        def now() -> float:
            return clock.now() - t0

        def flush_window() -> None:
            """Close one sketch/signal window (the serve-record
            cadence): land the window's delta sketches on the stream —
            bucket-wise mergeable into fleet-wide percentiles — and
            feed the live signal engine one observation."""
            nonlocal win_idx, win_t0, last_productive
            t = now()
            if writer.enabled and any(sk.count for sk in win_sk.values()):
                writer.event(
                    "latency_sketch", t=round(t, 4), window=win_idx,
                    fields={f: sk.to_record()
                            for f, sk in win_sk.items() if sk.count})
            measures: dict = {}
            causes: dict = {}
            if sig_target_ms and win_stats["n"]:
                measures["SUSTAINED_OVERLOAD"] = (win_stats["viol"]
                                                  / win_stats["n"])
                causes["SUSTAINED_OVERLOAD"] = {
                    "violations": win_stats["viol"],
                    "completed": win_stats["n"],
                    "target_ms": sig_target_ms}
            blk = win_stats["blocked"]
            if blk[0] + blk[1] > 1e-9:
                measures["KV_PRESSURE"] = blk[0] / (blk[0] + blk[1])
                causes["KV_PRESSURE"] = {
                    "pool_starved_s": round(blk[0], 4),
                    "batch_full_s": round(blk[1], 4),
                    "queued": len(queue),
                    "free_pages": (allocator.free_pages
                                   if allocator else None)}
            dt_win = t - win_t0
            if dt_win > 1e-9 and (queue or active):
                # goodput only means collapse while a backlog exists —
                # an idle engine between arrivals is not unhealthy
                gw = (productive_s - last_productive) / dt_win
                measures["GOODPUT_COLLAPSE"] = gw
                causes["GOODPUT_COLLAPSE"] = {
                    "window_goodput": round(gw, 4),
                    "queued": len(queue), "in_flight": len(active)}
            events = sig_engine.observe(round(t, 4), measures, causes)
            if events and signals_file:
                signals_mod.append_events(signals_file, events)
            for f in list(win_sk):
                win_sk[f] = sketch_mod.QuantileSketch()
            win_stats["n"] = win_stats["viol"] = 0
            win_stats["blocked"] = [0.0, 0.0]
            win_t0 = t
            last_productive = productive_s
            win_idx += 1

        def kv_pool_event() -> None:
            """One pool-ledger snapshot (the periodic cadence and the
            terminal flush share it): counters the engine already
            holds, no device round-trips.  Round 25 adds the growth/
            sharing/COW counters — pre-r25 readers see the keys as
            absent and normalize to 0."""
            writer.event(
                "kv_pool", t=round(now(), 4),
                pages_reserved=ledger.reserved_now,
                pages_written=ledger.written_now,
                free_pages=allocator.free_pages,
                pages_peak=allocator.pages_peak,
                pages_recycled=allocator.recycled,
                reserved_page_s=round(ledger.reserved_page_s, 6),
                written_page_s=round(ledger.written_page_s, 6),
                pages_grown=pages_grown_total,
                pages_cow=allocator.cow_copies,
                prefix_hits=prefix_hits,
                prefix_lookups=prefix_lookups,
                prefix_pages_shared=prefix_shared_total)

        def bucket_acct(kind: str, bucket: int, active_rows: int,
                        dt: float) -> None:
            u = butil.setdefault(f"{kind}@{bucket}", [0, 0, 0, 0.0])
            u[0] += 1
            u[1] += bucket
            u[2] += active_rows
            u[3] += dt

        def finish(fl: _InFlight, t_done: float, status: str = "ok",
                   cause: str | None = None) -> None:
            nonlocal finished, service_ewma_s, completed_ok
            finished += 1
            rec = {
                "id": fl.req.rid,
                # the terminal disposition every ledger exit stamps
                # (the retire-without-status lint pins call sites)
                "status": status,
                "arrival_s": round(fl.req.arrival_s, 6),
                "ttft_ms": round(
                    1e3 * ((fl.t_first if fl.t_first is not None
                            else t_done) - fl.req.arrival_s), 3),
                "e2e_ms": round(1e3 * (t_done - fl.req.arrival_s), 3),
                "prompt_len": fl.req.prompt_len,
                "output_len": fl.produced,
            }
            if cause:
                rec["cause"] = cause
            if fl.preempts:
                rec["preempts"] = fl.preempts
            # the conserved e2e decomposition (obs.requests): classify
            # members have no prompt pass, so their whole resident
            # window belongs to the decode lane (t_first := t_admit)
            rec.update(requests_mod.components_ms(
                fl.req.arrival_s, fl.t_admit,
                (fl.t_first if self.decode_mode and fl.t_first is not None
                 else fl.t_admit),
                fl.t_last if fl.t_last is not None else t_done,
                t_done, fl.active_s))
            # queue-wait cause split (obs.kv): which resource this
            # request's queue_ms was blocked on; the remainder (if any)
            # is arrival-to-first-scheduler-look alignment, not a
            # resource
            causes = wait_causes.pop(fl.req.rid, None) or [0.0, 0.0]
            rec["queue_pool_starved_ms"] = round(1e3 * causes[0], 3)
            rec["queue_batch_full_ms"] = round(1e3 * causes[1], 3)
            if self.decode_mode:
                # the greedy token ids (synthetic anyway) — the decode
                # parity tests and postmortems read them; <= 32 ints
                rec["generated"] = list(fl.out_tokens)
                # per-request KV footprint (obs.kv): the honesty gap —
                # worst-case pages reserved at admission vs pages that
                # ever held a token.  peak == final under worst-case
                # reservation; they diverge once mid-flight release
                # (on-demand paging) lands
                final_pages = ledger.retire(len(fl.pages), fl.length)
                rec["pages_reserved"] = len(fl.pages)
                rec["pages_peak_used"] = final_pages
                rec["pages_final"] = final_pages
                # round 25 footprint fields (absent on pre-r25 records;
                # readers normalize to 0, the r20/r22 seam): on-demand
                # growths after admission, and slots admitted pointing
                # at shared prefix-cache pages
                rec["pages_grown"] = fl.pages_grown
                rec["prefix_pages_shared"] = fl.prefix_shared
            if status == "ok":
                if not fl.preempts:
                    # the predictive-shed service estimate: first-admit
                    # to done of NEVER-preempted requests only — a
                    # requeued request's span includes its requeue wait,
                    # and folding that in spirals the estimate up until
                    # prediction sheds the whole queue
                    svc = t_done - fl.t_admit
                    service_ewma_s = (
                        svc if service_ewma_s is None
                        else 0.7 * service_ewma_s + 0.3 * svc)
                completed_ok += 1
                # the streaming percentile path (round 24): run- and
                # window-scoped sketches see every completion even
                # after the raw ring starts evicting
                for f in slo_mod.LATENCY_FIELDS:
                    v = rec.get(f)
                    if isinstance(v, (int, float)):
                        run_sk[f].add(float(v))
                        win_sk[f].add(float(v))
                win_stats["n"] += 1
                if sig_target_ms and rec["e2e_ms"] > sig_target_ms:
                    win_stats["viol"] += 1
                done.append(rec)
                writer.event("request", **rec)
            elif status == "shed":
                # degraded terminals land under their OWN record kind:
                # the percentile/attribution folds read kind=="request"
                # only, so a shed or quarantined request never skews
                # the served-latency percentiles
                degrade["shed"][cause] = degrade["shed"].get(cause, 0) + 1
                writer.event("shed", **rec)
                timeline_mod.instant("shed", rid=fl.req.rid, cause=cause)
            else:
                degrade["quarantined"] += 1
                writer.event("quarantine", **rec)
                timeline_mod.instant("quarantine", rid=fl.req.rid,
                                     cause=cause)
            timeline_mod.instant("retire", rid=fl.req.rid)
            if allocator is not None:
                allocator.free(fl.pages)

        def shed_queued(req: Request, cause: str, t: float) -> None:
            """Admit-time shed: the request never became resident, so
            there is no _InFlight to finish — but the disposition is
            terminal and carries its cause all the same."""
            nonlocal finished
            finished += 1
            degrade["shed"][cause] = degrade["shed"].get(cause, 0) + 1
            causes = wait_causes.pop(req.rid, None) or [0.0, 0.0]
            c = carry.pop(req.rid, None)
            rec = {
                "id": req.rid, "status": "shed", "cause": cause,
                "arrival_s": round(req.arrival_s, 6),
                "waited_ms": round(1e3 * (t - req.arrival_s), 3),
                "queue_pool_starved_ms": round(1e3 * causes[0], 3),
                "queue_batch_full_ms": round(1e3 * causes[1], 3),
            }
            if c:
                rec["preempts"] = c["preempts"]
            writer.event("shed", **rec)
            timeline_mod.instant("shed", rid=req.rid, cause=cause)

        def free_now() -> int:
            """Allocator free pages minus any injected pool squeeze —
            the admission path's ONE view of pool headroom."""
            f = allocator.free_pages
            if faults is not None:
                f -= faults.squeezed_pages(now())
            return max(0, f)

        def preempt_one() -> bool:
            """KV pressure: preempt the resident holding the most pages
            per token of progress and requeue it carrying its prefix.
            Victims must (a) have produced 2**preempts tokens THIS
            residency — a readmitted victim earns geometrically more
            decode progress before it is preemptible again, so every
            residency advances its request (no livelock) and the total
            re-prefill overhead a request can accrue is bounded by a
            constant factor of its output (no thrash under sustained
            pool pressure) — and (b) re-prefill prompt+prefix inside
            the warmed ladder (an off-ladder shape never runs).  With
            a deadline armed, victims must additionally have burned
            3/4 of their deadline: preempting a resident that can
            still finish in time converts pool pressure into
            re-prefill thrash AND a missed SLO, while one deep into
            its budget is about to expire holding pages anyway."""
            top = max(self.prefill_buckets)
            t_now = now()
            cands = [fl for fl in active
                     if fl.produced_res >= (1 << fl.preempts)
                     and fl.length <= top
                     and (not deadline_s or shed == "off"
                          or t_now - fl.req.arrival_s > 0.75 * deadline_s)]
            if not cands:
                return False
            victim = max(cands, key=lambda fl: len(fl.pages)
                         / max(1, fl.produced))
            active.remove(victim)
            ledger.retire(len(victim.pages), victim.length)
            allocator.free(victim.pages)
            carry[victim.req.rid] = {
                "prefix": list(victim.out_tokens),
                "t_admit": victim.t_admit, "t_first": victim.t_first,
                "active_s": victim.active_s, "t_last": victim.t_last,
                "preempts": victim.preempts + 1,
            }
            queue.append(victim.req)
            degrade["preempts"] += 1
            timeline_mod.instant("preempt", rid=victim.req.rid)
            timeline_mod.instant("requeue", rid=victim.req.rid)
            writer.event("preempt", rid=victim.req.rid,
                         cause="pool_starved",
                         pages_freed=len(victim.pages),
                         produced=victim.produced)
            return True

        def drain(t: float) -> dict:
            """SIGTERM drain: stop admitting, preempt every resident
            into the journal, and commit queued + not-yet-arrived
            requests with the checkpoint tmp->fsync->rename idiom —
            the serving lane's emergency checkpoint."""
            timeline_mod.instant("drain", queued=len(queue),
                                 in_flight=len(active))
            entries = []
            for fl in list(active):
                entries.append(faults_mod.journal_entry(
                    fl.req, produced=fl.produced,
                    prefix=list(fl.out_tokens),
                    preempts=fl.preempts + 1))
                if ledger is not None:
                    ledger.retire(len(fl.pages), fl.length)
                if allocator is not None:
                    allocator.free(fl.pages)
            active.clear()
            for req in queue:
                c = carry.pop(req.rid, None)
                pfx = c["prefix"] if c else ()
                entries.append(faults_mod.journal_entry(
                    req, produced=len(pfx), prefix=list(pfx),
                    preempts=c["preempts"] if c else 0))
            queue.clear()
            for req in pending[idx:]:
                entries.append(faults_mod.journal_entry(req))
            path = (journal_path or self.cfg.serve_journal
                    or os.path.join(
                        getattr(writer, "out_dir", None) or ".",
                        faults_mod.JOURNAL_NAME))
            faults_mod.write_journal(path, entries,
                                     model=self.cfg.model,
                                     seed=self.cfg.seed)
            writer.event("preempt", scope="drain", cause="sigterm",
                         t=round(t, 4), unfinished=len(entries),
                         journal=path)
            self.print_fn(
                f"serve drain: {len(entries)} unfinished request(s) "
                f"journaled to {path} — relaunch with "
                f"--serve_resume={path} to replay them")
            return {"journal": path, "unfinished": len(entries),
                    "reason": "sigterm"}

        def feed_of(req: Request, c: dict | None) -> np.ndarray:
            """The prefill token feed: the prompt, plus — for a
            requeued preemption victim — its generated prefix minus
            the newest token (the greedy pass regenerates that one,
            so resumption is exact: zero tokens lost or duplicated)."""
            if c and c["prefix"]:
                return np.concatenate(
                    [req.prompt,
                     np.asarray(c["prefix"][:-1], np.int32)])
            return req.prompt

        def need_pages(req: Request) -> int:
            """Pages admission must pull from the FREE list for this
            request right now: the full table under worst-case
            reservation; prompt + headroom minus the prefix-cache
            cover under lazy (the cache peek is pure — acquire
            happens inside admit in the same scheduler iteration)."""
            if kv_reserve == "worst":
                return self.table_width
            c = carry.get(req.rid)
            plen = req.prompt_len + (max(0, len(c["prefix"]) - 1)
                                     if c else 0)
            slots = min(self.table_width,
                        -(-plen // self.page_size) + headroom)
            if cache is not None:
                slots -= cache.match(feed_of(req, c)).slots
            return max(0, slots)

        def admit(req: Request) -> None:
            nonlocal kv, tokens_out, productive_s
            nonlocal prefix_hits, prefix_lookups, prefix_shared_total
            t_admit = now()
            c = carry.pop(req.rid, None)
            timeline_mod.instant("admit", rid=req.rid)
            if not self.decode_mode:
                active.append(_InFlight(req=req, pages=[],
                                        table=np.zeros(0, np.int32),
                                        t_admit=t_admit))
                return
            prefix = c["prefix"] if c else []
            if c:
                degrade["requeues"] += 1
            feed = feed_of(req, c)
            plen = int(len(feed))
            shared: list[int] = []
            m = None
            if cache is not None:
                prefix_lookups += 1
                m = cache.match(feed)
                if m.slots:
                    prefix_hits += 1
                    shared = cache.acquire(m)
                    prefix_shared_total += len(shared)
            if kv_reserve == "lazy":
                # reserve only what the prompt needs plus decode
                # headroom; every later page is an on-demand growth
                slots = min(self.table_width,
                            -(-plen // self.page_size) + headroom)
            else:
                slots = self.table_width
            fresh = allocator.alloc(max(0, slots - len(shared)))
            assert fresh is not None, "admission checked free_pages"
            pages = shared + fresh
            table = np.pad(np.asarray(pages, np.int32),
                           (0, self.table_width - len(pages)))
            ledger.admit(len(pages), plen)
            s = pick_bucket(self.prefill_buckets, plen)
            toks = np.zeros((1, s), np.int32)
            toks[0, :plen] = feed
            wtable = table
            if shared:
                # the prefill-skip seam: shared slots' physical pages
                # already hold this prefix's K/V bitwise (same params,
                # same absolute positions, deterministic prefill), so
                # the WRITE table routes their stores to trash page 0
                # — the decode table keeps the real shared ids.  The
                # dense pass itself still runs: next_token attends
                # over every prompt position either way.
                wtable = np.where(
                    np.arange(self.table_width) < len(shared),
                    0, table).astype(np.int32)
            (next_tok, logits, kv), dt = self._timed(
                clock, "prefill",
                lambda: self.compiled[("prefill", s)](
                    self.exec_params, kv, toks,
                    np.int32(plen), wtable))
            # host-side numpy view BEFORE indexing: jax.Array.__getitem__
            # dispatches a jitted gather — a post-warmup compile the
            # zero-recompile contract (and the cache-entry assertion)
            # would catch
            next_tok = np.asarray(next_tok)
            steps["prefill"] += 1
            if not c:
                # a re-prefill regenerates an already-counted token
                tokens_out += 1
            productive_s += dt * (plen / s)
            bucket_acct("prefill", s, plen, dt)
            ledger.charge(dt)
            fl = _InFlight(
                req=req, pages=pages, table=table, length=plen,
                produced=(len(prefix) if c else 1),
                last_token=int(next_tok[0]),
                t_admit=(c["t_admit"] if c else t_admit),
                t_first=(c["t_first"] if c else now()),
                out_tokens=(list(prefix[:-1]) + [int(next_tok[0])]
                            if c else [int(next_tok[0])]),
                active_s=(c["active_s"] + dt if c else 0.0),
                t_last=(c["t_last"] if c else None),
                preempts=(c["preempts"] if c else 0),
                produced_res=(0 if c else 1),
                prefix_shared=len(shared))
            if guard:
                row = np.asarray(logits)
                if faults is not None and faults.poison_rids([req.rid]):
                    row = np.full_like(np.array(row), np.nan)
                    announce_nan(req.rid, "prefill")
                if not np.isfinite(row).all():
                    fl.t_last = now()
                    finish(fl, now(), status="quarantined",
                           cause="nonfinite_logits")
                    return
            if cache is not None:
                # seed the trie with this prefill's pages (a finite,
                # non-quarantined pass only): full chunks as nodes,
                # the partial tail under its exact-token key — the
                # cache's own reference keeps them alive past this
                # request's retirement
                cache.insert(feed, pages, plen)
            if fl.produced >= req.output_len:
                finish(fl, now(), status="ok")
            else:
                active.append(fl)

        def announce_nan(rid: int, where: str) -> None:
            self.print_fn(f"inject: nan_logits rid {rid} ({where})")
            writer.event("injected_fault", fault="nan_logits", rid=rid,
                         where=where)

        def ensure_capacity(fl: _InFlight) -> bool:
            """Round 25 growth/COW pre-pass for one resident: make this
            step's append slot a writable, exclusively-owned page.
            Crossing a page boundary allocates from the free list AT
            THAT MOMENT (on-demand growth); the first append into a
            shared page duplicates it through the warmed page-copy
            program (copy-on-write).  Returns False to PAUSE the row
            this step — its batch slot masks off and nothing is
            written, so the next step retries after eviction,
            preemption, or a retirement frees pages."""
            nonlocal kv, pages_grown_total
            slot = fl.length // self.page_size
            if slot >= len(fl.pages):
                if free_now() < 1 and cache is not None:
                    cache.evict(1)
                if free_now() < 1:
                    return False
                grown = allocator.alloc(1)
                allocator.bind(fl.table, slot, grown[0])
                fl.pages.append(grown[0])
                ledger.grow(1)
                fl.pages_grown += 1
                pages_grown_total += 1
                return True
            page = fl.pages[slot]
            if allocator.refcount(page) == 1:
                return True
            # shared tail page (this holder + the cache and/or other
            # residents): copy before the write
            if free_now() < 1 and cache is not None:
                cache.evict(1)
            if free_now() < 1:
                return False
            dst = allocator.cow_alloc()
            (kv), dt = self._timed(
                clock, "page_copy",
                lambda: self.compiled[("page_copy", 0)](
                    kv, np.int32(page), np.int32(dst)))
            ledger.charge(dt)
            allocator.bind(fl.table, slot, dst)
            fl.pages[slot] = dst
            allocator.free([page])
            return True

        def decode_step() -> bool:
            nonlocal kv, tokens_out, productive_s
            if faults is not None:
                hang_s = faults.hang_before_decode(steps["decode"] + 1)
                if hang_s:
                    self.print_fn(f"inject: hang {hang_s}s before "
                                  f"decode step {steps['decode'] + 1}")
                    writer.event("injected_fault", fault="hang",
                                 step=steps["decode"] + 1,
                                 seconds=hang_s)
                    # REAL wall, whatever the engine clock: the wedged-
                    # host signature the watchdog's (real-time)
                    # progress oracle exists to catch
                    time.sleep(hang_s)
            sched = active
            if kv_reserve == "lazy" or cache is not None:
                sched = [fl for fl in active if ensure_capacity(fl)]
                if not sched and active and kv_preempt == "on" \
                        and preempt_one():
                    # every resident paused on growth: the r23
                    # machinery frees a victim's pages and the rest
                    # retry in the same step
                    sched = [fl for fl in active if ensure_capacity(fl)]
                if not sched:
                    return False
            b = pick_bucket(self.batch_buckets, len(sched))
            toks = np.zeros((b,), np.int32)
            tables = np.zeros((b, self.table_width), np.int32)
            lengths = np.zeros((b,), np.int32)
            mask = np.zeros((b,), bool)
            for i, fl in enumerate(sched):
                toks[i] = fl.last_token
                tables[i] = fl.table
                lengths[i] = fl.length
                mask[i] = True
            (next_toks, logits, kv), dt = self._timed(
                clock, "decode",
                lambda: self.compiled[("decode", b)](
                    self.exec_params, kv, toks, tables, lengths, mask))
            steps["decode"] += 1
            tokens_out += len(sched)
            productive_s += dt * (len(sched) / b)
            bucket_acct("decode", b, len(sched), dt)
            ledger.charge(dt)
            next_toks = np.asarray(next_toks)
            bad: set[int] = set()
            if guard:
                # per-request quarantine: ONE host read of the step's
                # logits, rows checked independently — a poisoned
                # request retires alone, batch-mates keep their
                # (finite) tokens
                lg = np.asarray(logits)[:len(sched)]
                hit = (set(faults.poison_rids(
                    [fl.req.rid for fl in sched]))
                    if faults is not None else set())
                if hit:
                    lg = np.array(lg)   # writable copy to poison
                    for i, fl in enumerate(sched):
                        if fl.req.rid in hit:
                            lg[i] = np.nan
                            announce_nan(fl.req.rid, "decode")
                finite = np.isfinite(lg.reshape(len(lg), -1)).all(axis=1)
                bad = {i for i in range(len(sched)) if not finite[i]}
            t_done = now()
            dropped: set[int] = set()
            for i, fl in enumerate(sched):
                fl.active_s += dt
                fl.t_last = t_done
                if i in bad:
                    finish(fl, t_done, status="quarantined",
                           cause="nonfinite_logits")
                    dropped.add(fl.req.rid)
                    continue
                fl.last_token = int(next_toks[i])
                fl.out_tokens.append(fl.last_token)
                ledger.token(fl.length)
                fl.length += 1
                fl.produced += 1
                fl.produced_res += 1
                if fl.produced >= fl.req.output_len:
                    finish(fl, t_done, status="ok")
                    dropped.add(fl.req.rid)
            if dropped:
                # paused rows (not in sched) keep their place; retire
                # by rid, not list rebuild from sched
                active[:] = [fl for fl in active
                             if fl.req.rid not in dropped]
            return True

        def classify_step() -> None:
            nonlocal tokens_out, productive_s
            b = pick_bucket(self.batch_buckets, len(active))
            x = np.zeros((b,) + tuple(self.spec.input_shape), np.float32)
            for i, fl in enumerate(active):
                x[i] = self._classify_input(fl.req)
            _, dt = self._timed(
                clock, "classify",
                lambda: self.compiled[("classify", b)](self.variables, x))
            steps["classify"] += 1
            tokens_out += len(active)
            productive_s += dt * (len(active) / b)
            bucket_acct("classify", b, len(active), dt)
            t_done = now()
            for fl in active:
                fl.t_first = t_done
                fl.produced = 1
                fl.active_s += dt
                fl.t_last = t_done
                finish(fl, t_done, status="ok")
            active.clear()

        # round 23: the drain handler + the scheduler-iteration
        # watchdog.  The engine installs a real SIGTERM/SIGINT handler
        # unless the caller injected one (tests poll a fake; install()
        # is a no-op off the main thread)
        own_handler = None
        handler = drain_handler
        if handler is None:
            own_handler = preempt_mod.PreemptionHandler(
                print_fn=self.print_fn).install()
            handler = own_handler
        timeout_s = watchdog_mod.resolve_timeout(
            step_timeout_s if step_timeout_s is not None
            else self.cfg.serve_step_timeout_s,
            warmup_step_s=(self.compile_record["warmup_s"]
                           / max(1, self.compile_record["buckets"])))
        last_iter_t: list = [None]

        def watchdog_forensics() -> None:
            # round-17 forensics on the serve lane: the flight-recorder
            # tail + the live-buffer memory dump, best-effort by
            # contract (both swallow their own failures)
            out_dir = getattr(writer, "out_dir", None)
            timeline_mod.dump_timeline(out_dir, "serve_watchdog",
                                       step=sum(steps.values()))
            if out_dir:
                from tpu_hc_bench.obs import memory as obs_memory
                obs_memory.dump_forensics(out_dir, "serve_watchdog",
                                          step=sum(steps.values()))

        dog = None
        if timeout_s:
            dog = watchdog_mod.Watchdog(
                timeout_s, lambda: last_iter_t[0],
                print_fn=self.print_fn,
                last_record_fn=lambda: getattr(writer, "last_record",
                                               None),
                obs_writer=writer if writer.enabled else None,
                on_timeout=on_watchdog,
                forensics_fn=watchdog_forensics).start()

        last_blocked: str | None = None
        try:
            while finished < n:
                t = now()
                while idx < n and pending[idx].arrival_s <= t:
                    queue.append(pending[idx])
                    idx += 1
                if faults is not None:
                    sq = faults.squeezed_pages(t)
                    if sq != squeezed_seen:
                        self.print_fn(
                            f"inject: pool_squeeze -> {sq} page(s) "
                            f"withheld at t={t:.3f}s")
                        writer.event("injected_fault",
                                     fault="pool_squeeze", pages=sq,
                                     t=round(t, 4))
                        squeezed_seen = sq
                    if faults.sigterm_due(t):
                        self.print_fn(f"inject: sigterm at t={t:.3f}s")
                        writer.event("injected_fault", fault="sigterm",
                                     t=round(t, 4))
                        faults.deliver_sigterm()
                if handler is not None and handler.requested():
                    drained = drain(t)
                    break
                queue_depths.append(len(queue))
                progressed = False
                if shed != "off":
                    # expiry pass: a request past its deadline decodes
                    # only dead tokens — shed it (queued) or retire it
                    # (resident) with a cause instead
                    for req in [r for r in queue
                                if t - r.arrival_s > deadline_s]:
                        queue.remove(req)
                        shed_queued(req, "deadline_expired", t)
                        progressed = True
                    for fl in [f for f in active
                               if t - f.req.arrival_s > deadline_s]:
                        active.remove(fl)
                        finish(fl, t, status="shed",
                               cause="resident_expired")
                        progressed = True
                if batching == "continuous":
                    while queue and len(active) < self.cap:
                        head = queue[0]
                        if (shed == "deadline"
                                and service_ewma_s is not None
                                and (now() - head.arrival_s)
                                + service_ewma_s > deadline_s):
                            # predictive shed: queue wait plus the
                            # admit-to-done EWMA already blows the
                            # deadline — reject at admission instead
                            # of decoding a dead answer
                            shed_queued(queue.popleft(),
                                        "deadline_predicted", now())
                            progressed = True
                            continue
                        if allocator is None \
                                or free_now() >= need_pages(head):
                            admit(queue.popleft())
                            progressed = True
                            continue
                        # starved: reclaim cold cache pages first (they
                        # are free capacity the trie is merely keeping
                        # warm), then the r23 preemption machinery
                        if cache is not None and cache.evict(
                                need_pages(head) - free_now()):
                            continue
                        if kv_preempt == "on" and preempt_one():
                            progressed = True
                            continue
                        break
                elif not active:
                    # static: wait for a full batch (or the trace
                    # tail); the batch is additionally bounded by what
                    # the KV pool can hold — resolve() only guarantees
                    # pages for ONE request, so a tuned half-pool row
                    # would otherwise crash admission (active empty =>
                    # every page is free)
                    want = min(self.cap, n - finished)
                    if allocator is not None:
                        want = min(want,
                                   free_now() // self.table_width)
                    if len(queue) >= want or idx == n:
                        for _ in range(min(want, len(queue))):
                            admit(queue.popleft())
                            progressed = True
                # admission forensics (round 22, obs.kv): when requests
                # stay queued past the admission pass, name the BINDING
                # resource — the scaling-policy input.  Continuous: a
                # full batch gates before a full pool (freeing pages
                # would not open a slot), so batch_full wins when both
                # bind.  Static: the run-to-completion batch policy is
                # always the gate — even a pool-capped batch admits
                # nothing mid-flight, so scale-out (not pool growth) is
                # the remedy.
                blocked_cause = None
                if queue:
                    if batching != "continuous":
                        blocked_cause = "batch_full"
                    elif len(active) >= self.cap:
                        blocked_cause = "batch_full"
                    elif allocator is not None and \
                            free_now() < need_pages(queue[0]):
                        blocked_cause = "pool_starved"
                if blocked_cause != last_blocked:
                    # edge-triggered flight-recorder instants: the
                    # moment admission blocks on (or frees from) a
                    # resource — bounded by transitions, not steps
                    if blocked_cause == "pool_starved":
                        timeline_mod.instant("pool_starved",
                                             queued=len(queue))
                    elif blocked_cause == "batch_full":
                        timeline_mod.instant("batch_full",
                                             queued=len(queue))
                    last_blocked = blocked_cause
                t_blocked = now()
                if active:
                    if self.decode_mode:
                        # a False return means every resident paused on
                        # growth/COW starvation — not progress
                        if decode_step():
                            progressed = True
                    else:
                        classify_step()
                        progressed = True
                if not progressed:
                    if idx >= n:
                        if shed == "off" or not queue:
                            raise RuntimeError(
                                "serve engine stalled: no request can "
                                "make progress — KV pool undersized? "
                                "(under --kv_reserve=lazy, "
                                "--kv_preempt=on frees pages by "
                                "preempting the worst resident)")
                        # shedding armed: a squeezed pool can pin the
                        # queue with nothing resident — idle to the
                        # next deadline; the expiry pass drains it
                        nxt = (min(r.arrival_s for r in queue)
                               + deadline_s)
                        clock.sleep(max(1e-4, nxt - now() + 1e-4))
                    else:
                        gap = pending[idx].arrival_s - now()
                        if timeout_s:
                            # chunked: an idle arrival gap must never
                            # read as a wedged scheduler
                            gap = min(gap, timeout_s / 2)
                        clock.sleep(gap)
                if blocked_cause is not None:
                    # charge the elapsed step/sleep to the blocking
                    # cause for every request that sat in queue through
                    # it (they rejoin admission at the next loop top)
                    dt_blk = now() - t_blocked
                    if dt_blk > 0:
                        ci = 0 if blocked_cause == "pool_starved" else 1
                        # the KV_PRESSURE measure: wall seconds this
                        # window spent blocked, split by binding cause
                        win_stats["blocked"][ci] += dt_blk
                        for r in queue:
                            wait_causes.setdefault(
                                r.rid, [0.0, 0.0])[ci] += dt_blk
                total_steps = sum(steps.values())
                if total_steps - last_record_step >= _SERVE_RECORD_EVERY:
                    last_record_step = total_steps
                    if writer.enabled:
                        writer.event(
                            "serve", t=round(now(), 4),
                            queue_depth=len(queue),
                            in_flight=len(active),
                            free_pages=(allocator.free_pages
                                        if allocator else None),
                            tokens=tokens_out,
                            # running per-bucket occupancy — `obs
                            # watch`'s live utilization column
                            bucket_occ={k: round(u[2] / u[1], 3)
                                        for k, u in butil.items()
                                        if u[1]},
                            **{f"{k}_steps": v
                               for k, v in steps.items()})
                        if ledger is not None:
                            kv_pool_event()
                    if fleet is not None:
                        fleet.heartbeat(
                            step=total_steps,
                            step_ewma_ms=1e3 * now()
                            / max(1, total_steps),
                            kv_peak_pages=(allocator.pages_peak
                                           if allocator else None),
                            phase="serve")
                    flush_window()
                # a completed scheduler iteration IS progress to the
                # watchdog — admission, shedding, and idle arrival
                # waits all count; only a wedged step does not
                last_iter_t[0] = time.perf_counter()
        finally:
            if dog is not None:
                dog.stop()
            if own_handler is not None:
                own_handler.uninstall()

        if self.decode_mode:
            self._kv = kv
        wall = max(now(), 1e-9)
        if ledger is not None and writer.enabled:
            # terminal ledger snapshot: runs shorter than one record
            # window still land their cumulative page-second integrals
            kv_pool_event()
        if fleet is not None:
            fleet.heartbeat(
                step=sum(steps.values()),
                step_ewma_ms=1e3 * wall / max(1, sum(steps.values())),
                kv_peak_pages=(allocator.pages_peak
                               if allocator else None),
                phase="serve")
        # the tail window (possibly under one record cadence) still
        # lands its sketch + one final signal observation
        flush_window()
        entries_final = self._count_cache()
        # summary percentiles come from the run-lifetime sketches —
        # exact over every completion, not just the retained ring
        fold = slo_mod.fold_sketches(run_sk)
        attribution = requests_mod.fold_attribution(list(done))
        kv_fold = None
        if ledger is not None:
            kv_fold = kv_mod.fold_ledger(
                reserved_page_s=ledger.reserved_page_s,
                written_page_s=ledger.written_page_s,
                pages_peak=allocator.pages_peak,
                pages_recycled=allocator.recycled,
                pages_grown=pages_grown_total,
                cow_copies=allocator.cow_copies,
                prefix_hits=prefix_hits,
                prefix_lookups=prefix_lookups,
                prefix_pages_shared=prefix_shared_total,
                request_records=list(done))
        summary = {
            "workload": "serve",
            "model": self.cfg.model,
            "batching": batching,
            "arrival": self.cfg.arrival,
            "arrival_rate": self.cfg.arrival_rate,
            "requests": n,
            "completed": completed_ok,
            "wall_s": round(wall, 4),
            "tokens": tokens_out,
            "tokens_per_s": round(tokens_out / wall, 3),
            "goodput": round(productive_s / wall, 4),
            "queue_depth_max": max(queue_depths, default=0),
            "queue_depth_mean": round(
                float(np.mean(queue_depths)) if queue_depths else 0.0, 3),
            "buckets": list(self.batch_buckets),
            "max_in_flight": self.cap,
            "kv_page_size": self.page_size,
            "kv_pages": self.num_pages,
            # round 22 (obs.kv): pool geometry + the utilization ledger
            "kv_layers": (self.family.num_layers
                          if self.decode_mode else None),
            "kv_pool_bytes": self.kv_pool_bytes,
            "kv_scale_bytes": self.kv_scale_bytes,
            "kv_pool": kv_fold,
            **kv_mod.flatten_kv(kv_fold),
            # round 25: the reservation/sharing arms are config
            # identity for this run (regress fingerprints on them)
            "kv_reserve": (kv_reserve if self.decode_mode else None),
            "prefix_cache": (prefix_cache if self.decode_mode
                             else None),
            "decode_attention": (self.decode_attention
                                 if self.decode_mode else None),
            "quant": self.quant,
            "decode_block_pages": self.compile_record.get(
                "decode_block_pages"),
            "aot_decode_temp_bytes": self.compile_record.get(
                "aot_decode_temp_bytes"),
            "post_warmup_compiles": entries_final
                                    - self.entries_after_warmup,
            # round 20 (obs.requests): the tail-attribution fold, its
            # regress projection, and the per-bucket occupancy account
            "attribution": attribution,
            **requests_mod.flatten_attribution(attribution),
            "bucket_util": {
                k: {"steps": u[0], "rows": u[1], "active_rows": u[2],
                    "wall_s": round(u[3], 4),
                    "occupancy": round(u[2] / u[1], 4) if u[1] else 0.0}
                for k, u in butil.items()},
            **{f"{k}_steps": v for k, v in steps.items()},
            **fold,
            # round 24: the mergeable-sketch account — source label,
            # retention cap, and the fleet-mergeable headline tail
            # (single host: the run sketch IS the merge of its
            # windows, so this equals p99_e2e_ms by construction)
            "latency_source": "sketch",
            "latency_sample_cap": _DONE_SAMPLE_CAP,
            "sketch_windows": win_idx,
            "p99_merged_ms": round(run_sk["e2e_ms"].quantile(99), 3),
            "signals_fired": dict(sorted(sig_engine.fired.items())),
            "signals_fired_total": sum(sig_engine.fired.values()),
        }
        # round 23 degradation account: always present so `obs regress`
        # can gate shed_frac against baselines that predate the knob
        shed_total = sum(degrade["shed"].values())
        summary["shed_frac"] = round(shed_total / max(1, n), 4)
        summary["degrade"] = {
            "shed": dict(sorted(degrade["shed"].items())),
            "shed_frac": summary["shed_frac"],
            "preempts": degrade["preempts"],
            "requeues": degrade["requeues"],
            "quarantined": degrade["quarantined"],
        }
        if drained is not None:
            summary["drained"] = drained
        if self.cfg.slo_e2e_ms:
            # windowed SLO burn rate: sustained overload vs transient
            # burst, against the --slo_e2e_ms e2e target
            summary["slo"] = slo_mod.fold_burn_rate(
                list(done), self.cfg.slo_e2e_ms)
        writer.event("serve_summary", **summary)
        writer.event("serve_compile", **self.compile_record,
                     entries_final=entries_final,
                     post_warmup_compiles=summary["post_warmup_compiles"])
        timeline_mod.detach()   # flush the serve spans, close the file
        return summary
