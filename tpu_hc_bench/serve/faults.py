"""Serve-lane fault injection + the drain/resume journal.

The training lane earned deterministic fault injection in round 8
(``resilience/inject.py``); this is the serving twin, sharing the same
``CLASS@WHERE[:ARG]`` grammar through ``inject.split_entries`` so both
lanes' specs parse — and fail — the same way:

- ``hang@STEP:SECONDS``   — the scheduler stalls SECONDS before decode
                            step STEP dispatches (the wedged-host
                            signature the serve watchdog exists for).
- ``nan_logits@RID``      — request RID's logits are poisoned
                            non-finite (host-side, after the compiled
                            call returns — injection must not recompile
                            a warmed bucket) the next time RID occupies
                            a prefill or decode row; exercises the
                            per-request quarantine path.
- ``sigterm@T``           — SIGTERM delivered to this process at
                            engine-clock T seconds; exercises the
                            drain → journal → exit-75 path.
- ``pool_squeeze@T:PAGES`` — PAGES KV pages withheld from the allocator
                            from engine-clock T seconds on (a sticky
                            external memory squeeze); exercises the
                            KV-pressure preemption/requeue path.

Entries may repeat.  Parsing is loud at flag time and the error names
BOTH lanes' vocabularies (``inject.malformed``).

The journal (``write_journal``/``read_journal``) is the drain path's
commit: every unfinished request — still queued, not yet arrived, or
preempted mid-generation — serialized with the tmp → fsync → rename
idiom the checkpoint sentinel uses, so a SIGTERM'd serving process
leaves either a complete journal or none, never a torn one.
``serve --serve_resume=<journal>`` replays every entry exactly once.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal

from tpu_hc_bench.resilience import inject as inject_mod

JOURNAL_NAME = "serve_journal.json"


@dataclasses.dataclass
class ServeFaultPlan:
    hang: dict[int, float]          # decode step -> seconds
    nan_logits: frozenset[int]      # request ids to poison
    sigterm: tuple[float, ...]      # engine-clock seconds
    pool_squeeze: tuple[tuple[float, int], ...]  # (t_s, pages) sticky

    def __bool__(self) -> bool:
        return bool(self.hang or self.nan_logits or self.sigterm
                    or self.pool_squeeze)

    # -- engine hooks (all host-side, all cheap when inert) ------------

    def hang_before_decode(self, decode_step: int) -> float:
        """Seconds to stall before decode step ``decode_step`` (0.0
        when none scheduled); one-shot per step number."""
        return self.hang.pop(decode_step, 0.0)

    def poison_rids(self, rids) -> list[int]:
        """The subset of ``rids`` whose logits rows must be poisoned
        this call (one-shot per rid: the quarantine retires it)."""
        if not self.nan_logits:
            return []
        hit = [r for r in rids if r in self.nan_logits]
        if hit:
            self.nan_logits = self.nan_logits - frozenset(hit)
        return hit

    def sigterm_due(self, t: float) -> bool:
        """True once per scheduled time <= ``t``; the caller delivers a
        REAL signal so the drain path under test is the production one."""
        due = [s for s in self.sigterm if s <= t]
        if due:
            self.sigterm = tuple(s for s in self.sigterm if s > t)
        return bool(due)

    def deliver_sigterm(self) -> None:
        os.kill(os.getpid(), signal.SIGTERM)

    def squeezed_pages(self, t: float) -> int:
        """KV pages withheld from the allocator at engine-clock ``t``
        (sticky: every trigger whose time has passed stays applied)."""
        return sum(p for at, p in self.pool_squeeze if t >= at)


def parse_serve_plan(spec: str | None) -> ServeFaultPlan | None:
    """Parse the --serve_faults grammar; None/empty spec -> None."""
    if not spec:
        return None
    hang: dict[int, float] = {}
    nan_logits: set[int] = set()
    sigterm: list[float] = []
    squeeze: list[tuple[float, int]] = []
    for cls, where, arg, entry in inject_mod.split_entries(
            spec, lane="serve"):
        try:
            if cls == "hang":
                if arg is None:
                    raise ValueError
                hang[_int_ge(where, 1)] = _pos_float(arg)
            elif cls == "nan_logits":
                if arg is not None:
                    raise ValueError
                nan_logits.add(_int_ge(where, 0))
            elif cls == "sigterm":
                if arg is not None:
                    raise ValueError
                sigterm.append(_nonneg_float(where))
            elif cls == "pool_squeeze":
                if arg is None:
                    raise ValueError
                squeeze.append((_nonneg_float(where), _int_ge(arg, 1)))
            else:
                raise ValueError
        except ValueError:
            raise ValueError(inject_mod.malformed(entry, "serve")) from None
    return ServeFaultPlan(hang=hang, nan_logits=frozenset(nan_logits),
                          sigterm=tuple(sorted(sigterm)),
                          pool_squeeze=tuple(sorted(squeeze)))


def _int_ge(s: str, floor: int) -> int:
    v = int(s)
    if v < floor:
        raise ValueError
    return v


def _pos_float(s: str) -> float:
    v = float(s)
    if v <= 0:
        raise ValueError
    return v


def _nonneg_float(s: str) -> float:
    v = float(s)
    if v < 0:
        raise ValueError
    return v


# ---------------------------------------------------------------------
# drain journal: the serving lane's "emergency checkpoint"


def journal_entry(req, produced: int = 0, prefix=None,
                  preempts: int = 0) -> dict:
    """One unfinished request as a journal row.  ``prefix`` (generated
    tokens so far) is carried for the record — the replay re-serves the
    request from scratch, which regenerates the same tokens from the
    same seeded model, so exactly-once means exactly one terminal
    record per rid in the resumed run."""
    prompt = getattr(req, "prompt", None)
    return {
        "rid": int(req.rid),
        "arrival_s": float(req.arrival_s),
        "prompt": None if prompt is None else [int(t) for t in prompt],
        "output_len": int(req.output_len),
        "produced": int(produced),
        "prefix": [int(t) for t in (prefix or ())],
        "preempts": int(preempts),
    }


def write_journal(path: str, entries: list[dict], *,
                  model: str | None = None, seed=None,
                  reason: str = "sigterm") -> str:
    """Commit the drain journal with tmp -> fsync -> rename (the
    checkpoint-sentinel idiom): a crash mid-write leaves no torn
    journal for ``--serve_resume`` to half-replay."""
    payload = {
        "kind": "serve_journal",
        "reason": reason,
        "model": model,
        "seed": seed,
        "unfinished": len(entries),
        "requests": sorted(entries, key=lambda e: (e["arrival_s"],
                                                   e["rid"])),
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_journal(path: str) -> dict:
    """Load + validate a drain journal; loud on a missing or non-journal
    file (a resume pointed at the wrong path must not silently serve
    zero requests)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("kind") != "serve_journal" \
            or not isinstance(payload.get("requests"), list):
        raise ValueError(
            f"{path} is not a serve drain journal (expected kind="
            f"'serve_journal' with a 'requests' list)")
    return payload


def journal_requests(payload: dict) -> list:
    """Journal rows -> ``arrivals.Request`` objects for the resumed
    run, arrival order preserved."""
    import numpy as np

    from tpu_hc_bench.serve.arrivals import Request

    out = []
    for row in payload["requests"]:
        prompt = row.get("prompt")
        out.append(Request(
            rid=int(row["rid"]),
            arrival_s=float(row["arrival_s"]),
            prompt=(None if prompt is None
                    else np.asarray(prompt, dtype=np.int32)),
            output_len=int(row["output_len"])))
    return out
