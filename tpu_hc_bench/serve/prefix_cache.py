"""Shared-prefix KV cache: a trie from prompt chunks to physical pages.

Round 25's sharing half of the vLLM story: the K/V rows for a prompt
position depend only on the tokens at and before it, so two prompts
that agree on their first ``k * page_size`` tokens produce bitwise-
identical KV pages for those k slots (same params, same absolute
positions, greedy/deterministic prefill).  This module maps page-
aligned prompt chunks to the physical page that already holds their
K/V, so a cache-hit admission points its page table at the shared
pages and skips the page WRITES for them — a table edit, not a kernel
change: the prefill program still runs its full dense pass (the next
token must see every prompt position), it just routes the stores for
shared slots to the reserved trash page 0.

Structure: a trie keyed on full ``page_size``-token chunk tuples.  A
node's path from the root spells the entire token prefix, which is
exactly the dependency closure of its page — two nodes can never
alias a page wrongly.  Partially-filled tail pages are cached too,
keyed by their exact tail-token tuple under the parent node: the tail
page of prompt ``[c0 | c1 | t0 t1]`` is reusable only by a prompt with
the same chunks AND the same tail, and because the OWNER of a cached
tail page appends into it on its first decode step, the tail entry is
what makes copy-on-write real traffic (refcount 2: owner + cache).

Refcount discipline: the cache holds ITS OWN reference on every page
it retains (``PageAllocator.share`` on insert), dropped through
``PageAllocator.free`` on eviction — the same incref/decref pairs a
resident request uses, so the ``page-refcount-discipline`` lint's
invariant (all page-table stores and free-list motion inside
``PageAllocator``) covers the cache for free.  Eviction is leaf-first
(tail partials, then childless nodes) in LRU order, and only touches
pages whose sole remaining holder is the cache — a page a resident
still reads is never reclaimed out from under it.

Host-side bookkeeping only: no jax import, no device transfers.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class PrefixMatch:
    """One lookup's result: the shared pages covering the longest
    cached prefix (full chunks first, optionally one tail partial),
    the token count they cover, and the trie path behind them (so
    ``acquire`` can touch LRU state without re-walking)."""

    pages: list
    tokens_covered: int
    nodes: list
    partial_key: tuple | None = None

    @property
    def slots(self) -> int:
        return len(self.pages)


class _Node:
    __slots__ = ("page", "children", "partials", "touched")

    def __init__(self, page=None):
        self.page = page                  # physical page id (None: root)
        self.children: dict = {}          # chunk tuple -> _Node
        self.partials: dict = {}          # tail tuple -> [page, touched]
        self.touched = 0


class PrefixCache:
    """The trie + its refcount holds.  One instance per engine run
    (it holds references into that run's ``PageAllocator``)."""

    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self.root = _Node()
        self._tick = 0
        self.cached_pages = 0
        self.evicted_pages = 0

    # -- lookup -------------------------------------------------------

    def _chunks(self, tokens) -> tuple[list[tuple], tuple]:
        ps = self.page_size
        toks = tuple(int(t) for t in tokens)
        full = len(toks) // ps
        return ([toks[j * ps:(j + 1) * ps] for j in range(full)],
                toks[full * ps:])

    def match(self, tokens) -> PrefixMatch:
        """Pure peek (no refcounts, no LRU motion): the longest cached
        prefix of ``tokens``, full chunks then at most one exact-tail
        partial.  Admission gates on it, then ``acquire``s the same
        match in the same scheduler iteration."""
        chunks, tail = self._chunks(tokens)
        node = self.root
        pages: list = []
        nodes: list = [node]
        for c in chunks:
            nxt = node.children.get(c)
            if nxt is None:
                return PrefixMatch(pages, len(pages) * self.page_size,
                                   nodes)
            node = nxt
            pages.append(node.page)
            nodes.append(node)
        covered = len(pages) * self.page_size
        if tail and tail in node.partials:
            pages = pages + [node.partials[tail][0]]
            return PrefixMatch(pages, covered + len(tail), nodes,
                               partial_key=tail)
        return PrefixMatch(pages, covered, nodes)

    def acquire(self, m: PrefixMatch) -> list:
        """Take one reference per shared page for an admitted request
        (released through the request's normal ``allocator.free`` at
        retirement) and touch the path's LRU clocks."""
        self._tick += 1
        for node in m.nodes:
            node.touched = self._tick
        if m.partial_key is not None:
            m.nodes[-1].partials[m.partial_key][1] = self._tick
        self.allocator.share(m.pages)
        return list(m.pages)

    # -- insert -------------------------------------------------------

    def insert(self, tokens, pages, length: int) -> int:
        """Cache the pages of a freshly-prefilled request: one trie
        node per full chunk, one partial entry for a non-empty tail.
        ``pages[j]`` must be the physical page of slot j.  Chunks
        already cached keep their canonical page (the caller's copy
        stays private).  Returns pages newly retained."""
        chunks, tail = self._chunks(tokens[:length])
        self._tick += 1
        node = self.root
        node.touched = self._tick
        added = 0
        walked = True
        for j, c in enumerate(chunks):
            nxt = node.children.get(c)
            if nxt is None:
                page = pages[j]
                if page == 0:
                    walked = False
                    break           # never cache the trash page
                nxt = _Node(page)
                self.allocator.share([page])
                node.children[c] = nxt
                added += 1
            nxt.touched = self._tick
            node = nxt
        if walked and tail:
            tslot = len(chunks)
            if tslot < len(pages) and tail not in node.partials:
                page = pages[tslot]
                if page != 0:
                    self.allocator.share([page])
                    node.partials[tail] = [page, self._tick]
                    added += 1
        self.cached_pages += added
        return added

    # -- eviction -----------------------------------------------------

    def _evictable(self):
        """Leaf candidates whose page only the cache still holds:
        ``(touched, kind, parent, key)`` rows — partials and childless,
        partial-free nodes (evicting leaves first keeps every retained
        node's path intact)."""
        out = []

        def walk(node):
            for key, entry in node.partials.items():
                if self.allocator.refcount(entry[0]) == 1:
                    out.append((entry[1], "partial", node, key))
            for key, child in node.children.items():
                if not child.children and not child.partials:
                    if self.allocator.refcount(child.page) == 1:
                        out.append((child.touched, "node", node, key))
                else:
                    walk(child)

        walk(self.root)
        return out

    def evict(self, need: int) -> int:
        """Free up to ``need`` pages back to the pool, coldest leaves
        first; returns how many were actually freed.  Evicting a leaf
        can expose its parent, so the scan repeats until satisfied or
        dry."""
        freed = 0
        while freed < need:
            cands = self._evictable()
            if not cands:
                break
            cands.sort(key=lambda c: c[0])
            for _, kind, parent, key in cands:
                if freed >= need:
                    break
                if kind == "partial":
                    page = parent.partials.pop(key)[0]
                else:
                    page = parent.children.pop(key).page
                self.allocator.free([page])
                freed += 1
        self.cached_pages -= freed
        self.evicted_pages += freed
        return freed
