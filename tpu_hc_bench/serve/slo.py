"""SLO report: fold ``request``/``serve`` records into latency/goodput
lines.

Pure record processing — NO jax import, by contract: ``obs summarize``,
``obs diff``, and ``obs watch`` call into this module, and the obs CLI
must keep rendering artifacts copied off a TPU VM on a laptop without a
backend.  The engine uses the same fold on its in-memory records, so
the driver's final print and the offline summarize agree by
construction.

Report fields (the serving analog of the training lane's
goodput/MFU/p50 account):

- **TTFT** p50/p95/p99 — arrival to first generated token (queueing +
  prefill; the interactivity number).
- **End-to-end** p50/p95/p99 — arrival to retirement.
- **tokens/s** — generated tokens over wall (the serving throughput
  headline).
- **goodput-under-load** — the fraction of wall spent on *useful*
  compute: each step's wall is credited at ``active_rows /
  bucket_rows`` (padding slots waste it) and idle waits credit
  nothing.  Static batching loses goodput twice — idling while a
  batch fills, and padding while stragglers finish — which is exactly
  the delta continuous batching exists to close.
- **queue depth** mean/max — the backpressure signal.
"""

from __future__ import annotations

SERVE_SUMMARY_KIND = "serve_summary"
REQUEST_KIND = "request"

# (label, key) rows shared by the summarize section and the diff table
DIFF_METRICS = (
    ("p99 ttft ms", "p99_ttft_ms"),
    ("p99 e2e ms", "p99_e2e_ms"),
    ("p50 e2e ms", "p50_e2e_ms"),
    ("tokens/s", "tokens_per_s"),
    ("serve goodput", "goodput"),
    ("queue max", "queue_depth_max"),
    # round 18: the decode-kernel win — worst decode bucket's AOT temp
    # bytes (the dense-gather temporaries the paged kernel eliminates)
    ("aot dec temp B", "aot_decode_temp_bytes"),
)


def percentile(values: list[float], q: float) -> float:
    """Linear-interpolated percentile (numpy convention) without the
    numpy import — this module renders on artifact-only machines."""
    if not values:
        return 0.0
    xs = sorted(values)
    if len(xs) == 1:
        return float(xs[0])
    pos = (q / 100.0) * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return float(xs[lo] * (1.0 - frac) + xs[hi] * frac)


def fold_requests(request_records: list[dict]) -> dict:
    """Percentile block from per-request records (engine-side and
    offline callers share it)."""
    out: dict = {}
    for field in ("ttft_ms", "e2e_ms", "queue_ms"):
        vals = [float(r[field]) for r in request_records
                if isinstance(r.get(field), (int, float))]
        for q in (50, 95, 99):
            out[f"p{q}_{field}"] = round(percentile(vals, q), 3)
    return out


def fold_serve_records(records: list[dict]) -> dict | None:
    """Fold one metrics stream's serving records, or None when the run
    has no serving lane (training runs cost one list scan).

    The last ``serve_summary`` record wins (engine-computed goodput and
    wall); percentiles are recomputed from the ``request`` records so a
    stream truncated before its summary still reports latencies.
    """
    reqs = [r for r in records if r.get("kind") == REQUEST_KIND]
    summaries = [r for r in records if r.get("kind") == SERVE_SUMMARY_KIND]
    compiles = [r for r in records if r.get("kind") == "serve_compile"]
    if not reqs and not summaries:
        return None
    fold: dict = {"completed": len(reqs)}
    if summaries:
        fold.update(summaries[-1])
        fold.pop("kind", None)
    if reqs:
        fold.update(fold_requests(reqs))
        fold["completed"] = len(reqs)
    if compiles:
        c = compiles[-1]
        fold.setdefault("post_warmup_compiles",
                        c.get("post_warmup_compiles"))
        fold["compile_buckets"] = c.get("buckets")
        fold["compile_warm"] = c.get("warm")
    return fold


def slo_lines(fold: dict) -> list[str]:
    """Render the serving section (summarize / the engine's final
    print; two-space indent matches the other summarize sections)."""
    lines = [
        f"  serve: {fold.get('completed', 0)}"
        + (f"/{fold['requests']}" if fold.get("requests") else "")
        + f" requests  batching={fold.get('batching', '?')}"
        + f"  arrival={fold.get('arrival', '?')}"
        + (f"@{fold.get('arrival_rate')}/s"
           if fold.get("arrival_rate") else ""),
    ]
    if "p50_ttft_ms" in fold:
        lines.append(
            f"  ttft ms p50 {fold['p50_ttft_ms']:.1f}  "
            f"p95 {fold['p95_ttft_ms']:.1f}  "
            f"p99 {fold['p99_ttft_ms']:.1f}   e2e ms "
            f"p50 {fold['p50_e2e_ms']:.1f}  "
            f"p95 {fold['p95_e2e_ms']:.1f}  "
            f"p99 {fold['p99_e2e_ms']:.1f}")
    if fold.get("wall_s") is not None:
        lines.append(
            f"  {fold.get('tokens', 0)} tokens in "
            f"{fold['wall_s']:.2f}s wall = "
            f"{fold.get('tokens_per_s', 0.0):.1f} tok/s   "
            f"goodput-under-load {fold.get('goodput', 0.0):.1%}   "
            f"queue depth mean {fold.get('queue_depth_mean', 0.0):.1f} "
            f"max {fold.get('queue_depth_max', 0)}")
    if fold.get("buckets"):
        lines.append(
            f"  buckets {','.join(str(b) for b in fold['buckets'])} "
            f"max_in_flight {fold.get('max_in_flight', '?')}  "
            f"kv {fold.get('kv_pages', '?')} pages x "
            f"{fold.get('kv_page_size', '?')} tokens  steps "
            f"prefill {fold.get('prefill_steps', 0)} / decode "
            f"{fold.get('decode_steps', 0)} / classify "
            f"{fold.get('classify_steps', 0)}")
    if fold.get("decode_attention"):
        tb = fold.get("aot_decode_temp_bytes")
        lines.append(
            f"  decode arm: attention={fold['decode_attention']} "
            f"quant={fold.get('quant', 'off')}"
            + (f" block_pages={fold['decode_block_pages']}"
               if fold.get("decode_block_pages") else "")
            + (f"  worst decode bucket AOT temp {tb / 2**20:.1f} MiB"
               if tb is not None else ""))
    pwc = fold.get("post_warmup_compiles")
    if pwc is not None:
        lines.append(
            f"  post-warmup compiles: {pwc}"
            + (" (every bucket warmed at startup)" if pwc == 0 else
               " — WARNING: shapes lowered mid-traffic"))
    return lines


def _pct(a: float, b: float) -> str:
    if a:
        return f"{(b - a) / a:+.1%}"
    return "new" if b else "-"


def serve_diff_lines(fold_a: dict | None, fold_b: dict | None) -> list[str]:
    """The ``obs diff`` serving rows (empty unless both runs serve)."""
    if not fold_a or not fold_b:
        return []
    lines = ["  serve metrics:"]
    for label, key in DIFF_METRICS:
        if key not in fold_a and key not in fold_b:
            continue
        va = float(fold_a.get(key) or 0.0)
        vb = float(fold_b.get(key) or 0.0)
        lines.append(f"  {label:>14s} {va:12.4g} {vb:12.4g} "
                     f"{_pct(va, vb):>8s}")
    if fold_a.get("batching") != fold_b.get("batching"):
        lines.append(f"  note: batching arm differs: "
                     f"{fold_a.get('batching')} -> "
                     f"{fold_b.get('batching')}")
    for key, label in (("decode_attention", "decode-attention arm"),
                       ("quant", "quant arm")):
        if fold_a.get(key) != fold_b.get(key):
            lines.append(f"  note: {label} differs: "
                         f"{fold_a.get(key)} -> {fold_b.get(key)}")
    return lines


def watch_lines(records: list[dict]) -> list[str]:
    """The live ``obs watch`` serving panel lines: last serve window +
    latest percentiles over the requests completed so far."""
    serves = [r for r in records if r.get("kind") == "serve"]
    fold = fold_serve_records(records)
    lines: list[str] = []
    if serves:
        s = serves[-1]
        lines.append(
            f"  serving t={s.get('t', 0.0):.1f}s  queue "
            f"{s.get('queue_depth', 0)}  in-flight "
            f"{s.get('in_flight', 0)}  free pages "
            f"{s.get('free_pages', '?')}  tokens {s.get('tokens', 0)}")
    if fold and "p99_e2e_ms" in fold and fold.get("completed"):
        lines.append(
            f"  {fold['completed']} done  p99 ttft "
            f"{fold['p99_ttft_ms']:.1f}ms  p99 e2e "
            f"{fold['p99_e2e_ms']:.1f}ms")
    return lines
