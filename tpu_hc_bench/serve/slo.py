"""SLO report: fold ``request``/``serve`` records into latency/goodput
lines.

Pure record processing — NO jax import, by contract: ``obs summarize``,
``obs diff``, and ``obs watch`` call into this module, and the obs CLI
must keep rendering artifacts copied off a TPU VM on a laptop without a
backend.  The engine uses the same fold on its in-memory records, so
the driver's final print and the offline summarize agree by
construction.

Report fields (the serving analog of the training lane's
goodput/MFU/p50 account):

- **TTFT** p50/p95/p99 — arrival to first generated token (queueing +
  prefill; the interactivity number).
- **End-to-end** p50/p95/p99 — arrival to retirement.
- **tokens/s** — generated tokens over wall (the serving throughput
  headline).
- **goodput-under-load** — the fraction of wall spent on *useful*
  compute: each step's wall is credited at ``active_rows /
  bucket_rows`` (padding slots waste it) and idle waits credit
  nothing.  Static batching loses goodput twice — idling while a
  batch fills, and padding while stragglers finish — which is exactly
  the delta continuous batching exists to close.
- **queue depth** mean/max — the backpressure signal.
"""

from __future__ import annotations

from tpu_hc_bench.obs import kv as kv_mod
from tpu_hc_bench.obs import requests as requests_mod
from tpu_hc_bench.obs import sketch as sketch_mod

SERVE_SUMMARY_KIND = "serve_summary"
REQUEST_KIND = "request"
# per-window mergeable quantile sketches (round 24): the engine lands
# one per serve-record window; summarize/diff merge them into
# fleet-wide percentiles next to the per-host stored-sample figures
SKETCH_KIND = "latency_sketch"
LATENCY_FIELDS = ("ttft_ms", "e2e_ms", "queue_ms")

# (label, key) rows shared by the summarize section and the diff table
DIFF_METRICS = (
    ("p99 ttft ms", "p99_ttft_ms"),
    ("p99 e2e ms", "p99_e2e_ms"),
    ("p50 e2e ms", "p50_e2e_ms"),
    # round 20: queue wait is the cheapest leading overload indicator
    # and has been on every request record since the lane opened
    ("p99 queue ms", "p99_queue_ms"),
    # round 24: the merged-sketch fleet-wide tail (absent on pre-r24
    # history; the row simply skips there)
    ("p99 e2e merged", "p99_e2e_ms_merged"),
    ("tokens/s", "tokens_per_s"),
    ("serve goodput", "goodput"),
    ("queue max", "queue_depth_max"),
    # round 18: the decode-kernel win — worst decode bucket's AOT temp
    # bytes (the dense-gather temporaries the paged kernel eliminates)
    ("aot dec temp B", "aot_decode_temp_bytes"),
)


def percentile(values: list[float], q: float) -> float:
    """Linear-interpolated percentile (numpy convention) without the
    numpy import — this module renders on artifact-only machines."""
    if not values:
        return 0.0
    xs = sorted(values)
    if len(xs) == 1:
        return float(xs[0])
    pos = (q / 100.0) * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return float(xs[lo] * (1.0 - frac) + xs[hi] * frac)


def request_sketches(request_records) -> dict:
    """One streaming sketch per latency field — O(buckets) memory over
    any stream length, and the same multiset of samples the engine's
    live sketches saw, so offline and engine-side folds agree."""
    sks = {f: sketch_mod.QuantileSketch() for f in LATENCY_FIELDS}
    for r in request_records:
        for field, sk in sks.items():
            v = r.get(field)
            if isinstance(v, (int, float)):
                sk.add(float(v))
    return sks


def fold_requests(request_records: list[dict]) -> dict:
    """Percentile block from per-request records (engine-side and
    offline callers share it).  Round 24: folded through the mergeable
    sketch (within its relative-error bound of the old stored-sample
    fold) so memory stays bounded over unbounded streams."""
    return fold_sketches(request_sketches(request_records))


def fold_sketches(sks: dict) -> dict:
    out: dict = {}
    for field in LATENCY_FIELDS:
        sk = sks.get(field)
        for q in (50, 95, 99):
            out[f"p{q}_{field}"] = round(sk.quantile(q), 3) if sk \
                else 0.0
    return out


def fold_serve_records(records: list[dict]) -> dict | None:
    """Fold one metrics stream's serving records, or None when the run
    has no serving lane (training runs cost one list scan).

    The last ``serve_summary`` record wins (engine-computed goodput and
    wall); percentiles are recomputed from the ``request`` records so a
    stream truncated before its summary still reports latencies.
    """
    reqs = [r for r in records if r.get("kind") == REQUEST_KIND]
    summaries = [r for r in records if r.get("kind") == SERVE_SUMMARY_KIND]
    compiles = [r for r in records if r.get("kind") == "serve_compile"]
    if not reqs and not summaries:
        return None
    fold: dict = {"completed": len(reqs)}
    if summaries:
        fold.update(summaries[-1])
        fold.pop("kind", None)
    if reqs:
        fold.update(fold_requests(reqs))
        fold["completed"] = len(reqs)
        # tail attribution recomputed from the request records, so a
        # stream truncated before its summary still attributes its p99
        # (pre-r20 records normalize to zero components, labeled)
        attr = requests_mod.fold_attribution(reqs)
        if attr is not None:
            fold["attribution"] = attr
        slo_t = (fold.get("slo") or {}).get("slo_e2e_ms") \
            if isinstance(fold.get("slo"), dict) else None
        if slo_t:
            fold["slo"] = fold_burn_rate(reqs, slo_t)
    # round 22 (obs.kv): the pool ledger recomputed from the stream so
    # a run truncated before its summary still reports utilization — a
    # pre-r22 stream folds to None and the keys stay absent, labeled
    kvf = kv_mod.fold_kv(records)
    if kvf is not None:
        fold["kv_pool"] = kvf
        fold.update(kv_mod.flatten_kv(kvf))
    fold.update(fold_window_sketches(records))
    if compiles:
        c = compiles[-1]
        fold.setdefault("post_warmup_compiles",
                        c.get("post_warmup_compiles"))
        fold["compile_buckets"] = c.get("buckets")
        fold["compile_warm"] = c.get("warm")
    return fold


def fold_window_sketches(records: list[dict]) -> dict:
    """Merge every ``latency_sketch`` window record in one stream (or
    several streams concatenated — merge is bucket-wise add, so the
    result IS the fleet-wide percentile, not an average of per-host
    ones).  A pre-r24 stream has no sketch records and folds to an
    empty dict — the keys stay absent, labeled, never a KeyError."""
    merged: dict[str, sketch_mod.QuantileSketch] = {}
    n_win = 0
    for r in records:
        if r.get("kind") != "latency_sketch":
            continue
        n_win += 1
        for f, srec in (r.get("fields") or {}).items():
            if not isinstance(srec, dict):
                continue
            sk = sketch_mod.QuantileSketch.from_record(srec)
            if f in merged:
                merged[f].merge(sk)
            else:
                merged[f] = sk
    if not merged:
        return {}
    out: dict = {"sketch_windows": n_win, "latency_source": "sketch"}
    for f, sk in merged.items():
        for q in (50, 95, 99):
            out[f"p{q}_{f}_merged"] = round(sk.quantile(q), 3)
    if "e2e_ms" in merged:
        out["p99_merged_ms"] = out["p99_e2e_ms_merged"]
    return out


DEFAULT_BURN_WINDOWS = 8


def fold_burn_rate(request_records: list[dict], slo_e2e_ms: float,
                   window_s: float | None = None) -> dict | None:
    """Windowed SLO violation tracking (round 20): violations per
    rolling window of completion time against an ``--slo_e2e_ms``
    target — a transient burst lights up one window, sustained
    overload lights up a *streak*, which endpoint-wide violation
    counts cannot distinguish.

    ``window_s`` defaults to the run span / ``DEFAULT_BURN_WINDOWS``.
    Returns None when no target or no completions.
    """
    if not slo_e2e_ms or slo_e2e_ms <= 0:
        return None
    done = []
    for r in request_records:
        e2e, arr = r.get("e2e_ms"), r.get("arrival_s")
        if isinstance(e2e, (int, float)) and isinstance(arr, (int, float)):
            done.append((float(arr) + float(e2e) / 1e3, float(e2e)))
    if not done:
        return None
    done.sort()
    t_lo, t_hi = done[0][0], done[-1][0]
    span = max(t_hi - t_lo, 1e-9)
    if window_s is None or window_s <= 0:
        window_s = span / DEFAULT_BURN_WINDOWS
    # ceil-based bin count with the t_hi completion clamped into the
    # last FULL bin — int(span/w)+1 would put the boundary completion
    # alone in a degenerate trailing window, skewing peak rate and the
    # streak/SUSTAINED denominators
    n_win = max(1, int(-(-span // window_s)))
    wins = [{"t": round(t_lo + i * window_s, 4), "n": 0, "violations": 0}
            for i in range(n_win)]
    violations = 0
    for t, e2e in done:
        i = min(int((t - t_lo) / window_s), n_win - 1)
        wins[i]["n"] += 1
        if e2e > slo_e2e_ms:
            wins[i]["violations"] += 1
            violations += 1
    streak = best_streak = 0
    peak_rate, peak_t = 0.0, wins[0]["t"]
    for w in wins:
        w["rate"] = round(w["violations"] / w["n"], 4) if w["n"] else 0.0
        if w["violations"]:
            streak += 1
            best_streak = max(best_streak, streak)
        else:
            streak = 0
        if w["rate"] > peak_rate:
            peak_rate, peak_t = w["rate"], w["t"]
    return {
        "slo_e2e_ms": slo_e2e_ms,
        "window_s": round(window_s, 4),
        "completed": len(done),
        "violations": violations,
        "violation_rate": round(violations / len(done), 4),
        "peak_window_rate": round(peak_rate, 4),
        "peak_window_t": round(peak_t, 4),
        "max_violation_streak": best_streak,
        "windows": wins,
    }


def burn_lines(burn: dict | None) -> list[str]:
    """The one summarize/engine line for the SLO burn account."""
    if not burn:
        return []
    n_win = len(burn.get("windows", ()))
    return [
        f"  slo: e2e <= {burn['slo_e2e_ms']:g}ms — "
        f"{burn['violations']}/{burn['completed']} violated "
        f"({burn['violation_rate']:.1%}); worst window "
        f"{burn['peak_window_rate']:.0%} @ t={burn['peak_window_t']:.1f}s; "
        f"longest streak {burn['max_violation_streak']}/{n_win} "
        f"window(s)"
        + (" — SUSTAINED overload" if n_win
           and burn["max_violation_streak"] >= max(2, n_win // 2)
           else "")
    ]


def slo_lines(fold: dict) -> list[str]:
    """Render the serving section (summarize / the engine's final
    print; two-space indent matches the other summarize sections)."""
    lines = [
        f"  serve: {fold.get('completed', 0)}"
        + (f"/{fold['requests']}" if fold.get("requests") else "")
        + f" requests  batching={fold.get('batching', '?')}"
        + f"  arrival={fold.get('arrival', '?')}"
        + (f"@{fold.get('arrival_rate')}/s"
           if fold.get("arrival_rate") else ""),
    ]
    if "p50_ttft_ms" in fold:
        lines.append(
            f"  ttft ms p50 {fold['p50_ttft_ms']:.1f}  "
            f"p95 {fold['p95_ttft_ms']:.1f}  "
            f"p99 {fold['p99_ttft_ms']:.1f}   e2e ms "
            f"p50 {fold['p50_e2e_ms']:.1f}  "
            f"p95 {fold['p95_e2e_ms']:.1f}  "
            f"p99 {fold['p99_e2e_ms']:.1f}")
    if "p99_e2e_ms_merged" in fold:
        # round 24: the fleet-wide merged-sketch tail, source-labeled
        # next to the per-host stored-sample figures above
        lines.append(
            f"  e2e ms [sketch, {fold.get('sketch_windows', '?')} "
            f"window(s) merged] p50 {fold['p50_e2e_ms_merged']:.1f}  "
            f"p95 {fold['p95_e2e_ms_merged']:.1f}  "
            f"p99 {fold['p99_e2e_ms_merged']:.1f}")
    if "p50_queue_ms" in fold:
        # queue wait: the cheapest leading indicator of overload —
        # folded since round 16, rendered since round 20
        lines.append(
            f"  queue ms p50 {fold['p50_queue_ms']:.1f}  "
            f"p99 {fold['p99_queue_ms']:.1f}")
    # round 20 (obs.requests): where the p99 lives
    lines.extend(requests_mod.attribution_lines(
        fold.get("attribution"), p99_e2e_ms=fold.get("p99_e2e_ms")))
    # round 22 (obs.kv): utilization headline + honesty gap + the
    # tail-cause split + configured pool geometry
    lines.extend(kv_mod.kv_lines(fold))
    lines.extend(burn_lines(fold.get("slo")))
    # round 23: the degradation account — sheds by cause, preemption/
    # requeue traffic, quarantined poison requests.  Rendered only when
    # the engine actually degraded; a clean run stays a clean report.
    deg = fold.get("degrade")
    if deg and (deg.get("shed") or deg.get("preempts")
                or deg.get("quarantined")):
        shed = deg.get("shed") or {}
        parts = [f"shed {sum(shed.values())}"
                 + (" (" + ", ".join(
                     f"{c}x{shed[c]}" for c in kv_mod.SHED_CAUSES
                     if c in shed) + ")" if shed else "")]
        if deg.get("preempts"):
            parts.append(f"preempts {deg['preempts']} "
                         f"(requeued {deg.get('requeues', 0)})")
        if deg.get("quarantined"):
            parts.append(f"quarantined {deg['quarantined']}")
        lines.append(
            f"  degrade: {'  '.join(parts)}   "
            f"shed_frac {deg.get('shed_frac', 0.0):.1%}")
    if fold.get("wall_s") is not None:
        lines.append(
            f"  {fold.get('tokens', 0)} tokens in "
            f"{fold['wall_s']:.2f}s wall = "
            f"{fold.get('tokens_per_s', 0.0):.1f} tok/s   "
            f"goodput-under-load {fold.get('goodput', 0.0):.1%}   "
            f"queue depth mean {fold.get('queue_depth_mean', 0.0):.1f} "
            f"max {fold.get('queue_depth_max', 0)}")
    if fold.get("buckets"):
        lines.append(
            f"  buckets {','.join(str(b) for b in fold['buckets'])} "
            f"max_in_flight {fold.get('max_in_flight', '?')}  "
            f"kv {fold.get('kv_pages', '?')} pages x "
            f"{fold.get('kv_page_size', '?')} tokens  steps "
            f"prefill {fold.get('prefill_steps', 0)} / decode "
            f"{fold.get('decode_steps', 0)} / classify "
            f"{fold.get('classify_steps', 0)}")
    if fold.get("decode_attention"):
        tb = fold.get("aot_decode_temp_bytes")
        lines.append(
            f"  decode arm: attention={fold['decode_attention']} "
            f"quant={fold.get('quant', 'off')}"
            + (f" block_pages={fold['decode_block_pages']}"
               if fold.get("decode_block_pages") else "")
            + (f"  worst decode bucket AOT temp {tb / 2**20:.1f} MiB"
               if tb is not None else ""))
    # round 20: per-bucket occupancy heatmap (padding waste and ladder
    # sizing read directly off it)
    lines.extend(requests_mod.bucket_util_lines(fold.get("bucket_util")))
    pwc = fold.get("post_warmup_compiles")
    if pwc is not None:
        lines.append(
            f"  post-warmup compiles: {pwc}"
            + (" (every bucket warmed at startup)" if pwc == 0 else
               " — WARNING: shapes lowered mid-traffic"))
    return lines


def _pct(a: float, b: float) -> str:
    if a:
        return f"{(b - a) / a:+.1%}"
    return "new" if b else "-"


def serve_diff_lines(fold_a: dict | None, fold_b: dict | None) -> list[str]:
    """The ``obs diff`` serving rows (empty unless both runs serve)."""
    if not fold_a or not fold_b:
        return []
    lines = ["  serve metrics:"]
    for label, key in DIFF_METRICS:
        if key not in fold_a and key not in fold_b:
            continue
        va = float(fold_a.get(key) or 0.0)
        vb = float(fold_b.get(key) or 0.0)
        lines.append(f"  {label:>14s} {va:12.4g} {vb:12.4g} "
                     f"{_pct(va, vb):>8s}")
    if fold_a.get("batching") != fold_b.get("batching"):
        lines.append(f"  note: batching arm differs: "
                     f"{fold_a.get('batching')} -> "
                     f"{fold_b.get('batching')}")
    for key, label in (("decode_attention", "decode-attention arm"),
                       ("quant", "quant arm")):
        if fold_a.get(key) != fold_b.get(key):
            lines.append(f"  note: {label} differs: "
                         f"{fold_a.get(key)} -> {fold_b.get(key)}")
    # round 20: component deltas over the slowest decile — a pre-r20
    # side normalizes to zero components, labeled, never a KeyError
    lines.extend(requests_mod.attribution_diff_lines(
        fold_a.get("attribution"), fold_b.get("attribution")))
    # round 22: utilization / honesty-gap / tail-cause deltas — same
    # absent-not-error seam for a pre-r22 side
    lines.extend(kv_mod.kv_diff_lines(fold_a, fold_b))
    return lines


def watch_lines(records: list[dict]) -> list[str]:
    """The live ``obs watch`` serving panel lines: last serve window +
    latest percentiles over the requests completed so far."""
    serves = [r for r in records if r.get("kind") == "serve"]
    fold = fold_serve_records(records)
    lines: list[str] = []
    if serves:
        s = serves[-1]
        lines.append(
            f"  serving t={s.get('t', 0.0):.1f}s  queue "
            f"{s.get('queue_depth', 0)}  in-flight "
            f"{s.get('in_flight', 0)}  free pages "
            f"{s.get('free_pages', '?')}  tokens {s.get('tokens', 0)}")
        occ = s.get("bucket_occ")
        if occ:
            # live per-bucket occupancy column (round 20)
            lines.append("  bucket occ: " + "  ".join(
                f"{k} {v:.0%}" for k, v in sorted(occ.items())))
    pools = [r for r in records if r.get("kind") == kv_mod.KV_POOL_KIND]
    if pools:
        # live pool-occupancy column (round 22): reserved vs actually
        # written right now, plus the running high-water
        p = pools[-1]
        res = int(p.get("pages_reserved") or 0)
        wrt = int(p.get("pages_written") or 0)
        lines.append(
            f"  kv pool: {res} reserved / {wrt} written / "
            f"{p.get('free_pages', '?')} free pages  "
            f"peak {p.get('pages_peak', '?')}  "
            f"recycled {p.get('pages_recycled', '?')}")
    if fold and "p99_e2e_ms" in fold and fold.get("completed"):
        lines.append(
            f"  {fold['completed']} done  p99 ttft "
            f"{fold['p99_ttft_ms']:.1f}ms  p99 e2e "
            f"{fold['p99_e2e_ms']:.1f}ms"
            + (f"  merged[sketch] p99 {fold['p99_merged_ms']:.1f}ms"
               if fold.get("p99_merged_ms") is not None else ""))
    return lines
