"""Device-topology discovery, worker-layout math, and mesh construction.

The reference computes its process layout from CPU topology with ``lscpu``
(``benchmark-scripts/run-tf-sing-ucx-openmpi.sh:37-38``) and pure shell
arithmetic (``:40-50``)::

    NUM_SOCKETS, CORES_PER_SOCKET   <- lscpu
    WORKERS_PER_SOCKET == 0  =>  1 worker/node, all cores        (:40-46)
    else                     =>  WORKERS_PER_NODE = W * NUM_SOCKETS
                                 CORES_PER_WORKER = CORES_PER_SOCKET / W
    INTRA_T = CORES_PER_WORKER / 2                                (:48-49)
    TOTAL_WORKERS = NUM_NODES * WORKERS_PER_NODE                  (:50)

then pins one MPI rank per worker with exclusive cores
(``--map-by ppr:W:socket,pe=C``, ``:102``) — topology-aware data parallelism.

The TPU-native translation (SURVEY.md §7 stage 1): a *worker* is a TPU chip,
a *node* is a TPU-VM host, and placement is a ``jax.sharding.Mesh`` laid out
so the data-parallel axis rides ICI within a host slice and DCN across
slices.  ``workers_per_host`` keeps the reference's ``WORKERS_PER_SOCKET``
contract: ``0`` means "use every local chip" (the whole-machine mode of
``:40-46``), ``k`` means "use k chips per host".
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
from jax.sharding import Mesh

# Mesh axis names.  Only "data" is used for reference parity (the reference
# is DP-only, SURVEY.md §2c); the others exist so the mesh abstraction does
# not preclude tensor/pipeline/sequence sharding later.
DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
# Multislice: the slice-crossing data-parallel axis.  Collectives over it
# ride DCN (slices have no ICI between them); the step builders reduce
# gradients over (dcn, data) so XLA emits the hierarchical allreduce —
# reduce-scatter within a slice over ICI, the small cross-slice exchange
# over DCN (the round-3 `fabric=dcn` mechanism).
DCN_AXIS = "dcn"


@dataclasses.dataclass(frozen=True)
class Layout:
    """Resolved worker layout — the TPU analog of the reference's :40-50 math."""

    num_hosts: int           # NUM_NODES analog (launcher arg 1)
    chips_per_host: int      # discovered, analog of lscpu sockets*cores
    workers_per_host: int    # resolved (0 -> chips_per_host)
    total_workers: int       # TOTAL_WORKERS (:50) == DP degree

    @property
    def global_batch_size(self) -> int:
        raise AttributeError("use global_batch(per_worker_batch)")

    def global_batch(self, per_worker_batch: int) -> int:
        """Reference semantics: --batch_size is *per worker* (README.md:70)."""
        return per_worker_batch * self.total_workers

    def summary_lines(self, fabric: str = "ici") -> list[str]:
        """Resolved-layout banner, mirroring run-tf-sing-ucx-openmpi.sh:52-58."""
        return [
            f"num_hosts={self.num_hosts} chips_per_host={self.chips_per_host}",
            f"workers_per_host={self.workers_per_host} "
            f"total_workers={self.total_workers} fabric={fabric}",
        ]


def compute_layout(
    num_hosts: int,
    workers_per_host: int,
    chips_per_host: int,
) -> Layout:
    """Pure layout math (testable without devices).

    Mirrors run-tf-sing-ucx-openmpi.sh:40-50 with chips in place of cores:
    ``workers_per_host == 0`` selects whole-host mode (all chips, one DP
    group member per chip — on TPU every chip is always its own worker, so
    whole-host mode means "all local chips participate").
    """
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    if workers_per_host < 0:
        raise ValueError(f"workers_per_host must be >= 0, got {workers_per_host}")
    if chips_per_host < 1:
        raise ValueError(f"chips_per_host must be >= 1, got {chips_per_host}")
    resolved = chips_per_host if workers_per_host == 0 else workers_per_host
    if resolved > chips_per_host:
        raise ValueError(
            f"workers_per_host={resolved} exceeds chips_per_host={chips_per_host}"
        )
    return Layout(
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        workers_per_host=resolved,
        total_workers=num_hosts * resolved,
    )


def discover_layout(
    num_hosts: int | None = None,
    workers_per_host: int = 0,
    devices: Sequence[jax.Device] | None = None,
) -> Layout:
    """Layout from live device topology (the lscpu replacement, :37-38)."""
    devices = list(devices if devices is not None else jax.devices())
    hosts = sorted({d.process_index for d in devices})
    discovered_hosts = len(hosts)
    chips_per_host = sum(1 for d in devices if d.process_index == hosts[0])
    return compute_layout(
        num_hosts=num_hosts if num_hosts is not None else discovered_hosts,
        workers_per_host=workers_per_host,
        chips_per_host=chips_per_host,
    )


def select_devices(
    layout: Layout, devices: Sequence[jax.Device] | None = None
) -> list[jax.Device]:
    """Pick ``workers_per_host`` chips on each host, in stable id order.

    The analog of the reference's exclusive-core rank pinning
    (``--map-by ppr:W:socket,pe=C``, :102): a deterministic, contiguous
    device selection so ICI neighbors stay adjacent in the mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    by_host: dict[int, list[jax.Device]] = {}
    for d in sorted(devices, key=lambda d: d.id):
        by_host.setdefault(d.process_index, []).append(d)
    hosts = sorted(by_host)[: layout.num_hosts]
    picked: list[jax.Device] = []
    for h in hosts:
        local = by_host[h]
        if len(local) < layout.workers_per_host:
            raise ValueError(
                f"host {h} has {len(local)} chips < "
                f"workers_per_host={layout.workers_per_host}"
            )
        picked.extend(local[: layout.workers_per_host])
    return picked


# ---------------------------------------------------------------------
# Checkpoint topology descriptors (elastic resume, round 12)
#
# A production preemptible fleet does not restart on the mesh it died
# on: nodes are re-imaged and re-assembled, and the benchmark must
# resume on whatever is alive (the reference's cluster-self-assembly
# premise, PAPER.md).  Every checkpoint therefore records a small
# *topology sidecar* — the layout facts restore needs to decide whether
# the saved state drops straight onto the live mesh, needs a reshard
# (zero1's [N, k] optimizer shards), or is genuinely incompatible.
# ``topology_record`` builds it, ``elastic_plan`` is the one home of the
# compatibility policy, and ``utils.checkpoint``/``train.driver``
# enforce it (--resume=elastic).

# what on-disk form the checkpoint took: "host" = host-gathered full
# arrays (replicated DP/SP/TP single-process, zero1 gather-on-save),
# "sharded" = multi-host per-shard Orbax jax.Array I/O, "pp-native" =
# the stacked [L, ...] pipeline trunk layout of save_pp
CKPT_LAYOUTS = ("host", "sharded", "pp-native")
# arms whose on-disk state tree is identical (replicated params + a
# param-shaped optimizer state): transitions inside this set are free
REPLICATED_ARMS = ("psum", "replicated")


def topology_record(layout: Layout, mesh: Mesh, cfg,
                    layout_kind: str = "host") -> dict:
    """The checkpoint topology sidecar: everything ``restore`` must know
    about the world that wrote a checkpoint to re-place it elsewhere."""
    if layout_kind not in CKPT_LAYOUTS:
        raise ValueError(f"layout_kind must be one of {CKPT_LAYOUTS}: "
                         f"{layout_kind!r}")
    return {
        "schema": 1,
        "world": int(layout.total_workers),
        "process_count": int(jax.process_count()),
        "mesh": {str(k): int(v) for k, v in dict(mesh.shape).items()},
        "variable_update": cfg.variable_update,
        "pipeline_parallel": int(getattr(cfg, "pipeline_parallel", 1) or 1),
        "layout": layout_kind,
        "dtype": cfg.compute_dtype,
    }


def _mesh_str(rec: dict | None) -> str:
    """Render a record's mesh dict as ``data:8xmodel:1`` (``?`` when
    absent) — the ONE home of the rendering, shared by the mismatch
    error and the elastic plan line."""
    mesh = "x".join(f"{k}:{v}"
                    for k, v in ((rec or {}).get("mesh") or {}).items())
    return mesh or "?"


def describe_topology(rec: dict | None) -> str:
    """One-line human rendering of a topology record (mismatch errors
    and the elastic-resume plan line both use it)."""
    if not rec:
        return "unknown (no topology sidecar)"
    return (f"world={rec.get('world')} mesh=[{_mesh_str(rec)}] "
            f"arm={rec.get('variable_update')} "
            f"pp={rec.get('pipeline_parallel', 1)} "
            f"layout={rec.get('layout')} dtype={rec.get('dtype')}")


def elastic_plan(saved: dict, live: dict) -> tuple[str, str]:
    """Compare a checkpoint's recorded topology against the live one.

    Returns ``(action, line)``:

    - ``("ok", "")`` — identical topology; restore as always.
    - ``("noop", plan)`` — topologies differ but the on-disk form is
      layout-neutral (host-layout replicated trees restore onto any
      mesh; pp-native stacked global shapes are pipe-degree independent
      and Orbax re-places them).  ``plan`` is the one-line note the
      driver prints.
    - ``("reshard", plan)`` — restorable, but only through the elastic
      path (``--resume=elastic``): zero1's gathered ``[N, k]`` optimizer
      shards must be resplit to the new world size.
    - ``("refuse", reason)`` — genuinely incompatible: the state trees
      differ (zero1 vs replicated optimizer, pp-native vs DP layout) or
      the shards are not reassemblable here (multi-host model-sharded
      saves).
    """
    same = all(
        saved.get(k) == live.get(k)
        for k in ("world", "mesh", "variable_update", "pipeline_parallel",
                  "layout"))
    if same:
        return "ok", ""
    s_arm = saved.get("variable_update")
    l_arm = live.get("variable_update")
    s_lay = saved.get("layout", "host")
    l_lay = live.get("layout", "host")
    sw, lw = saved.get("world"), live.get("world")
    if (s_arm == "zero1") != (l_arm == "zero1"):
        return ("refuse",
                f"arm {s_arm}->{l_arm}: the zero1 optimizer-state tree "
                f"(stacked [N, k] shards) and the replicated one are "
                f"different structures — resume on --variable_update="
                f"{s_arm}, or restart fresh")
    if ("pp-native" in (s_lay, l_lay)) and s_lay != l_lay:
        return ("refuse",
                f"layout {s_lay}->{l_lay}: pp-native stacked-trunk "
                f"checkpoints and DP-layout ones are different trees — "
                f"resume under the saved layout")
    if "sharded" in (s_lay, l_lay):
        return ("refuse",
                f"layout {s_lay}->{l_lay} with world {sw}->{lw}: "
                f"multi-host model-sharded checkpoints resume on the "
                f"saved topology only (per-shard Orbax I/O is not "
                f"host-reassemblable here)")
    extra = ("" if saved.get("dtype") == live.get("dtype")
             else f"; note: dtype policy {saved.get('dtype')}->"
                  f"{live.get('dtype')} (params restore bitwise, compute "
                  f"dtype changes)")
    if s_arm == "zero1":        # and l_arm == "zero1"
        return ("reshard",
                f"zero1 optimizer shards resplit [{sw}, k]->[{lw}, k'] "
                f"over the data axis (world {sw}->{lw}){extra}")
    return ("noop",
            f"replicated {s_arm} state re-placed onto the live mesh "
            f"(world {sw}->{lw}, mesh [{_mesh_str(saved)}]->"
            f"[{_mesh_str(live)}]){extra}")


def build_mesh(
    layout: Layout,
    devices: Sequence[jax.Device] | None = None,
    model_parallel: int = 1,
    pipeline_parallel: int = 1,
    sequence_parallel: int = 1,
    num_slices: int = 1,
    force_seq_axis: bool = False,
) -> Mesh:
    """Build the device mesh for this layout.

    DP-only (reference parity) gives a ``("data", "model")`` mesh with a
    size-1 model axis.  Minor degrees > 1 append their axes; *multiple*
    minor degrees compose into a hybrid 3-D (or 4-D) mesh — e.g.
    ``pipeline_parallel=2, model_parallel=2`` on 8 devices yields a
    ``(data=2, pipe=2, model=2)`` mesh (round-2: the one-minor-axis
    restriction is lifted; DPxPPxTP and DPxSPxTP are the supported hybrid
    step compositions, see train/step.py and parallel/pipeline.py).

    Axis order = collective frequency: ``model`` innermost (an all-reduce
    per layer rides adjacent-chip ICI), then ``seq`` (per-attention
    ppermute ring), then ``pipe`` (one hop per microbatch tick), ``data``
    outermost (one gradient reduction per step, the only axis that may
    cross hosts/DCN).

    Device order: host-major, chip-minor — the data axis crosses hosts last,
    so intra-host ICI carries the short allreduce hops and DCN only the
    inter-host phase (the `ib` fast path of run-tf-sing-ucx-openmpi.sh:85-92
    by construction).

    ``num_slices > 1`` is the explicit **multislice** layout
    (slices x hosts/slice x chips): a leading ``dcn`` axis of that size
    splits the data dimension, contiguous host groups form the slices
    (host-major order makes slice-major equal host-major), and the step
    builders reduce over ``(dcn, data)`` so the cross-slice phase of the
    gradient allreduce is explicit in the program — the round-3 mechanism
    behind ``fabric=dcn`` (the reference's second transport stack,
    run-tf-sing-libfabric-intelmpi.sh:86-105, as a mesh axis).
    """
    import numpy as np

    minors = [(PIPE_AXIS, pipeline_parallel), (SEQ_AXIS, sequence_parallel),
              (MODEL_AXIS, model_parallel)]
    for name, deg in minors:
        if deg < 1:
            raise ValueError(f"{name} degree must be >= 1, got {deg}")
    # force_seq_axis: keep a size-1 seq axis (degenerate SP — the
    # seq-sharded attention impls need the axis name bound even at world 1)
    active = [(name, deg) for name, deg in minors
              if deg > 1 or (name == SEQ_AXIS and force_seq_axis)]
    picked = select_devices(layout, devices)
    n = len(picked)
    prod = 1
    for _, deg in active:
        prod *= deg
    if n % prod:
        raise ValueError(
            f"{n} devices not divisible by the minor-axis product "
            f"{prod} ({'x'.join(f'{nm}={d}' for nm, d in active)})")
    if not active:
        active = [(MODEL_AXIS, 1)]      # preserve the 2-D DP mesh shape
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if num_slices > 1:
        # real pods: contiguous host groups form slices; a single-host
        # (virtual) mesh may still split into slices for testing
        if layout.num_hosts > 1 and layout.num_hosts % num_slices:
            raise ValueError(
                f"num_slices={num_slices} does not divide "
                f"num_hosts={layout.num_hosts}")
        data = n // prod
        if data % num_slices:
            raise ValueError(
                f"data degree {data} not divisible by num_slices="
                f"{num_slices}")
        shape = (num_slices, data // num_slices) + tuple(
            deg for _, deg in active)
        arr = np.array(picked, dtype=object).reshape(shape)
        return Mesh(arr, (DCN_AXIS, DATA_AXIS)
                    + tuple(name for name, _ in active))
    shape = (n // prod,) + tuple(deg for _, deg in active)
    arr = np.array(picked, dtype=object).reshape(shape)
    return Mesh(arr, (DATA_AXIS,) + tuple(name for name, _ in active))
