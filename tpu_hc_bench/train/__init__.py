"""Training: the TPU-native replacement for tf_cnn_benchmarks' train loop +
Horovod DistributedOptimizer (SURVEY.md §3.1 per-step hot loop)."""

from tpu_hc_bench.train.step import TrainState, make_train_state, build_train_step  # noqa: F401
from tpu_hc_bench.train.driver import run_benchmark, BenchmarkResult, log_name  # noqa: F401
