"""The benchmark driver: tf_cnn_benchmarks' measurement protocol on TPU.

Reproduces the reference's experiment shape exactly
(``run-tf-sing-ucx-openmpi.sh:32-35,71``): ``num_warmup_batches`` untimed
steps (covering compile — the analog of the reference's warmup absorbing
graph build + MKL priming), then ``num_batches`` timed steps, throughput
printed every ``display_every`` steps, and a final ``total images/sec``
line — the metric the operator greps from the teed log (SURVEY.md §5
observability row).  Adds what the reference lacks: per-chip throughput,
step-time stats, and MFU against the chip's peak (BASELINE.md targets).
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable

import jax
import numpy as np

from tpu_hc_bench.flags import BenchmarkConfig
from tpu_hc_bench.models import create_model
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
from tpu_hc_bench.parallel import fabric as fabric_mod
from tpu_hc_bench.topology import Layout, build_mesh, discover_layout
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.utils import hw


@dataclasses.dataclass
class BenchmarkResult:
    model: str
    total_workers: int
    global_batch: int
    total_images_per_sec: float      # "total images/sec" (tf_cnn final line)
    images_per_sec_per_chip: float
    mean_step_ms: float
    p50_step_ms: float
    mfu: float
    final_loss: float
    fabric: str

    def json_line(self) -> dict:
        return dataclasses.asdict(self)


def log_name(
    num_hosts: int, batch: int, data: str, fabric: str, run: int = 1
) -> str:
    """Log naming convention, after the reference's
    ``tfmn-<n>n-<b>b-<data>-<fabric>-r<run>.log`` (run-tf-sing-ucx-openmpi.sh:9-12)."""
    return f"tpubench-{num_hosts}n-{batch}b-{data}-{fabric}-r{run}.log"


def _example_units(cfg: BenchmarkConfig, spec) -> str:
    return "examples" if spec.is_text else "images"


def _prefetch(gen, lookahead: int = 2):
    """Keep `lookahead` device batches in flight.

    jax.device_put is asynchronous, so pulling the generator ahead of the
    consumer overlaps host decode + host->device DMA with the running step
    (the tf.data prefetch-to-device role in the reference's pipeline).
    """
    import collections

    q = collections.deque()
    for item in gen:
        q.append(item)
        if len(q) >= lookahead:
            yield q.popleft()
    while q:
        yield q.popleft()


def _run_eval(cfg, spec, layout, mesh, state, batch_iter, global_batch,
              fab, print_fn):
    """tf_cnn_benchmarks --eval: timed forward passes + top-1 accuracy."""
    from tpu_hc_bench.train import step as step_mod

    eval_step = step_mod.build_eval_step(mesh, cfg, spec)
    units = _example_units(cfg, spec)
    for _ in range(max(1, min(cfg.num_warmup_batches, 5))):
        loss, correct = eval_step(state, next(batch_iter))
    jax.block_until_ready(loss)

    correct_total = 0.0
    seen = 0
    step_times = []
    for i in range(1, cfg.num_batches + 1):
        t0 = time.perf_counter()
        loss, correct = eval_step(state, next(batch_iter))
        jax.block_until_ready(loss)
        step_times.append(time.perf_counter() - t0)
        correct_total += float(jax.device_get(correct))
        seen += global_batch
        if i % cfg.display_every == 0 or i == cfg.num_batches:
            print_fn(
                f"{i}\ttop_1: {correct_total / seen:.4f}\t"
                f"loss: {float(jax.device_get(loss)):.3f}"
            )
    total_time = sum(step_times)
    total_rate = cfg.num_batches * global_batch / total_time
    per_chip = total_rate / layout.total_workers
    peak = hw.peak_flops(dtype=cfg.compute_dtype)
    result = BenchmarkResult(
        model=cfg.model,
        total_workers=layout.total_workers,
        global_batch=global_batch,
        total_images_per_sec=total_rate,
        images_per_sec_per_chip=per_chip,
        mean_step_ms=1e3 * total_time / cfg.num_batches,
        p50_step_ms=1e3 * statistics.median(step_times),
        mfu=(spec.flops_per_example * per_chip) / peak,
        final_loss=float(jax.device_get(loss)),
        fabric=fab.value,
    )
    print_fn("-" * 40)
    print_fn(f"eval top_1 accuracy: {correct_total / seen:.4f}")
    print_fn(f"total {units}/sec: {total_rate:.2f}")
    return result


def run_benchmark(
    cfg: BenchmarkConfig,
    layout: Layout | None = None,
    fabric_name: str = "ici",
    print_fn: Callable[[str], None] = print,
    model_dtype=None,
) -> BenchmarkResult:
    """Run the full benchmark protocol; returns the measured result."""
    import jax.numpy as jnp

    fab = fabric_mod.resolve_fabric(fabric_name)
    layout = layout or discover_layout()
    mesh = build_mesh(layout)
    global_batch = layout.global_batch(cfg.batch_size)

    dtype = model_dtype or jnp.dtype(cfg.compute_dtype)
    model, spec = create_model(cfg.model, num_classes=cfg.num_classes,
                               dtype=dtype, attention_impl=cfg.attention_impl)

    # --- banner (reference :52-58 config echo) ---
    for line in layout.summary_lines(fabric=fab.value):
        print_fn(line)
    for line in cfg.summary_lines():
        print_fn(line)
    fcfg = fabric_mod.FabricConfig(fab, cfg.fusion_threshold_bytes)
    print_fn(fcfg.summary())
    print_fn(f"device_kind={hw.device_kind()} global_batch={global_batch}")

    # --- data ---
    if cfg.data_dir is not None and not spec.is_text:
        # real ImageNet TFRecords, per-host shard split (reference :19,80-81)
        from tpu_hc_bench.data.imagenet import ImageNetDataset

        image_size = spec.default_image_size
        split = "train"
        if cfg.eval:
            # prefer a validation split when present (standard layout);
            # fall back to train shards otherwise
            from tpu_hc_bench.data.imagenet import find_shards

            try:
                find_shards(cfg.data_dir, "validation")
                split = "validation"
            except FileNotFoundError:
                pass
        ds = ImageNetDataset(
            cfg.data_dir,
            global_batch=global_batch,
            image_size=image_size,
            split=split,
            train=not cfg.eval,
            worker=jax.process_index(),
            num_workers=jax.process_count(),
            seed=cfg.seed,
        )
        host_iter = iter(ds)
        batch = next(host_iter)

        def batches():
            def raw():
                yield step_mod.shard_batch(batch, mesh)
                for b in host_iter:
                    yield step_mod.shard_batch(b, mesh)
            yield from _prefetch(raw())
    elif spec.is_text:
        seq_len = spec.input_shape[0]
        ds = SyntheticTokens(global_batch, seq_len, seed=cfg.seed)
        batch = ds.batch()

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh)
            while True:
                yield dev_batch
    else:
        ds = SyntheticImages(
            global_batch, spec.input_shape, num_classes=cfg.num_classes,
            seed=cfg.seed,
        )
        batch = ds.batch()

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh)
            while True:
                yield dev_batch

    # --- state + step ---
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    batch_iter = batches()
    if cfg.eval:
        return _run_eval(
            cfg, spec, layout, mesh, state, batch_iter, global_batch,
            fab, print_fn,
        )
    train_step = step_mod.build_train_step(mesh, cfg, spec, fab)
    rng = jax.random.PRNGKey(cfg.seed + 17)

    # --- warmup (includes compile; reference warmup=50, :32) ---
    t_compile = time.perf_counter()
    metrics = None
    for _ in range(max(1, cfg.num_warmup_batches)):
        state, metrics = train_step(state, next(batch_iter), rng)
    jax.block_until_ready(state.params)
    print_fn(
        f"warmup done: {cfg.num_warmup_batches} steps in "
        f"{time.perf_counter() - t_compile:.1f}s (includes compile)"
    )

    # optional jax.profiler trace over the first few timed steps — the
    # structured replacement for the reference's I_MPI_DEBUG=5 fabric
    # tracing (run-tf-sing-libfabric-intelmpi.sh:98)
    tracing = False
    if cfg.trace_dir:
        jax.profiler.start_trace(cfg.trace_dir)
        tracing = True

    # --- timed loop (reference num_batches=100, display_every=10) ---
    units = _example_units(cfg, spec)
    step_times: list[float] = []
    losses: list[float] = []
    window_start = time.perf_counter()
    for i in range(1, cfg.num_batches + 1):
        t0 = time.perf_counter()
        state, metrics = train_step(state, next(batch_iter), rng)
        jax.block_until_ready(metrics["loss"])
        step_times.append(time.perf_counter() - t0)
        if tracing and i >= min(5, cfg.num_batches):
            jax.profiler.stop_trace()
            tracing = False
            print_fn(f"profiler trace written to {cfg.trace_dir}")
        if i % cfg.display_every == 0 or i == cfg.num_batches:
            now = time.perf_counter()
            window_steps = (
                cfg.display_every if i % cfg.display_every == 0
                else i % cfg.display_every
            )
            rate = window_steps * global_batch / (now - window_start)
            loss = float(jax.device_get(metrics["loss"]))
            losses.append(loss)
            print_fn(f"{i}\t{units}/sec: {rate:.1f}\tloss: {loss:.3f}")
            window_start = now

    total_time = sum(step_times)
    total_rate = cfg.num_batches * global_batch / total_time
    per_chip = total_rate / layout.total_workers
    mean_ms = 1e3 * total_time / cfg.num_batches
    p50_ms = 1e3 * statistics.median(step_times)

    # MFU: fwd+bwd ~= 3x forward FLOPs; forward-only runs use 1x
    flops_mult = 1.0 if cfg.forward_only else 3.0
    peak = hw.peak_flops(dtype=cfg.compute_dtype)
    mfu = (flops_mult * spec.flops_per_example * per_chip) / peak

    result = BenchmarkResult(
        model=cfg.model,
        total_workers=layout.total_workers,
        global_batch=global_batch,
        total_images_per_sec=total_rate,
        images_per_sec_per_chip=per_chip,
        mean_step_ms=mean_ms,
        p50_step_ms=p50_ms,
        mfu=mfu,
        final_loss=losses[-1] if losses else float("nan"),
        fabric=fab.value,
    )
    print_fn("-" * 40)
    print_fn(f"total {units}/sec: {total_rate:.2f}")
    print_fn(
        f"{units}/sec/chip: {per_chip:.2f}  step: {mean_ms:.2f}ms "
        f"(p50 {p50_ms:.2f}ms)  MFU: {100 * mfu:.1f}%"
    )
    return result
