"""The benchmark driver: tf_cnn_benchmarks' measurement protocol on TPU.

Reproduces the reference's experiment shape exactly
(``run-tf-sing-ucx-openmpi.sh:32-35,71``): ``num_warmup_batches`` untimed
steps (covering compile — the analog of the reference's warmup absorbing
graph build + MKL priming), then ``num_batches`` timed steps, throughput
printed every ``display_every`` steps, and a final ``total images/sec``
line — the metric the operator greps from the teed log (SURVEY.md §5
observability row).  Adds what the reference lacks: per-chip throughput,
step-time stats, and MFU against the chip's peak (BASELINE.md targets).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable

import jax
import numpy as np

from tpu_hc_bench import flags as flags_mod
from tpu_hc_bench.flags import BenchmarkConfig
from tpu_hc_bench.obs import efficiency as obs_efficiency
from tpu_hc_bench.obs import fleet as obs_fleet
from tpu_hc_bench.obs import goodput as obs_goodput
from tpu_hc_bench.obs import memory as obs_memory
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import timeline as timeline_mod
from tpu_hc_bench.models import create_model
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
from tpu_hc_bench.parallel import fabric as fabric_mod
from tpu_hc_bench.resilience import (
    guards as guards_mod, inject as inject_mod, preempt as preempt_mod,
    watchdog as watchdog_mod,
)
from tpu_hc_bench.resilience.retry import retry_io
from tpu_hc_bench.topology import (
    DATA_AXIS, Layout, SEQ_AXIS, build_mesh, discover_layout,
    topology_record,
)
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.utils import hw
from tpu_hc_bench.utils.sync import drain


@dataclasses.dataclass
class BenchmarkResult:
    model: str
    total_workers: int
    global_batch: int
    total_images_per_sec: float      # "total images/sec" (tf_cnn final line)
    images_per_sec_per_chip: float
    mean_step_ms: float
    # weighted median of per-step times at COMPLETION-MARKER granularity:
    # every step enqueues a marker and the fetch thread coalesces under
    # backlog; p50_step_granularity is the width (in steps) of the
    # interval the median came from — 1 means the reported p50 is a true
    # per-step time, N > 1 means it was measured over an N-step window
    # (tunnel RTT > step time) and the label admits it instead of
    # passing window medians off as per-step
    p50_step_ms: float
    p50_step_granularity: int
    mfu: float
    final_loss: float
    fabric: str
    # wall-clock goodput fraction (obs.goodput ledger): productive step
    # seconds / wall seconds; NaN where no ledger ran (eval, PP arms)
    goodput: float = float("nan")
    # the ledger's phase breakdown (phase -> wall seconds, zero phases
    # omitted): how the non-productive wall was spent — compile,
    # checkpoint blocking, data waits.  None where no ledger ran.
    goodput_phases: dict | None = None
    # fraction of wall spent blocked on the input pipeline (the
    # ledger's data_wait phase / wall seconds); NaN where no ledger ran.
    # THE input-service success metric: ~0 as workers-per-host scale
    data_wait_frac: float = float("nan")
    # which input arm actually fed the run: True = shared host service,
    # False = per-process pipeline, None = no real-image input plane.
    # --input_service=auto resolves inside the driver, so the flag
    # string alone cannot distinguish the arms in a run record
    input_service: bool | None = None
    # where the MFU's FLOP figure came from: "measured" =
    # compiled.cost_analysis() of the actual step program, "analytic" =
    # the hand-maintained spec.flops_per_example table (obs.efficiency)
    mfu_source: str = "analytic"
    # resume identity when this run restored a checkpoint (None for a
    # fresh run): restored_step, saved_world -> live_world, arm, and
    # whether the elastic reshard ran — so `obs diff`/BENCH json can
    # attribute a post-resume throughput shift to the topology change
    resume: dict | None = None
    # measured device memory (obs.memory): the run's HBM high-water mark
    # (allocator peak where the backend exposes one, the live-array
    # byte-sum high water otherwise — mem_source says which), the device
    # limit, and the step program's AOT memory_analysis() byte account
    # (None on runs where the probe didn't run)
    peak_hbm_bytes: int | None = None
    hbm_bytes_limit: int | None = None
    mem_source: str | None = None
    memory_analysis: dict | None = None

    def json_line(self) -> dict:
        return dataclasses.asdict(self)


def log_name(
    num_hosts: int, batch: int, data: str, fabric: str, run: int = 1
) -> str:
    """Log naming convention, after the reference's
    ``tfmn-<n>n-<b>b-<data>-<fabric>-r<run>.log`` (run-tf-sing-ucx-openmpi.sh:9-12)."""
    return f"tpubench-{num_hosts}n-{batch}b-{data}-{fabric}-r{run}.log"


def _example_units(cfg: BenchmarkConfig, spec) -> str:
    if (spec.is_text or getattr(spec, "ctc", False)
            or getattr(spec, "integer_input", False)):
        return "examples"
    return "images"


def _prefetch(gen, lookahead: int = 2):
    """Keep `lookahead` device batches in flight (``--prefetch_depth``).

    jax.device_put is asynchronous, so pulling the generator ahead of the
    consumer overlaps host decode + host->device DMA with the running step
    (the tf.data prefetch-to-device role in the reference's pipeline).
    """
    import collections

    q = collections.deque()
    for item in gen:
        q.append(item)
        if len(q) >= lookahead:
            yield q.popleft()
    while q:
        yield q.popleft()


def _cache_entry_count(cache_dir: str) -> int:
    """Files under the compile-cache dir — the hit/miss denominator:
    entries that appear between run start and end-of-warmup are the
    compile-cache misses this run paid for."""
    import os

    count = 0
    for _, _, files in os.walk(cache_dir):
        count += len(files)
    return count


def _resolve_compile_cache(cfg: BenchmarkConfig, print_fn) -> str | None:
    """Resolve ``--compile_cache`` into an ACTIVE persistent-compile-
    cache dir (or None), before anything lowers.

    Policy: ``off`` disables; an explicit dir is always honored (loud
    warning on jax<0.5, where executing cache-deserialized CPU
    executables has corrupted the heap on some programs — the
    tests/conftest.py note); unset = auto: a cache dir already
    configured on ``jax.config`` is reused untouched (the test
    harness's shared cache, an operator's env), else ``--train_dir``
    implies ``<train_dir>/compile_cache`` on capable stacks.
    """
    import os

    from tpu_hc_bench._compat import CAPABILITIES

    spec = cfg.compile_cache
    if spec is not None and spec.strip().lower() in ("off", "none", "0",
                                                     ""):
        return None
    existing = None
    try:
        existing = jax.config.jax_compilation_cache_dir
    except Exception:
        pass
    if spec:
        cache_dir = spec
    elif existing:
        return existing
    elif cfg.train_dir and CAPABILITIES["persistent_compilation_cache"]:
        cache_dir = os.path.join(cfg.train_dir, "compile_cache")
    else:
        return None
    if not CAPABILITIES["persistent_compilation_cache"]:
        print_fn(
            "WARNING: --compile_cache on a jax<0.5 stack: executing "
            "cache-deserialized CPU executables has corrupted the heap "
            "on some programs (tests/conftest.py note); honoring the "
            "explicit flag anyway")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        # cache sub-second compiles too: warm-start wins on small
        # programs are the point, and entries are cheap
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception:
        pass
    return cache_dir


def _input_service_on(cfg: BenchmarkConfig, layout) -> bool:
    """Resolve ``--input_service`` against the world shape.

    ``auto`` turns the service on exactly when >1 worker shares one
    host (the oversubscription case it exists for); ``on`` with workers
    spread over several hosts is refused loudly — per-host worker
    grouping is not derivable here, and a cross-host shm ring is
    nonsense.  flags.resolve already translated the config-level
    exclusions (synthetic input, repeat_cached_sample, eval) to off.
    """
    if cfg.input_service == "off":
        return False
    if cfg.datasets_repeat_cached_sample or cfg.eval:
        # auto never engages for these (resolve() already translated an
        # explicit on to off with a note): repeat_cached shuts the
        # pipeline down after a handful of batches, and eval reads the
        # validation split per-process
        return False
    world = jax.process_count()
    if cfg.input_service == "on":
        if world > 1 and layout.num_hosts > 1:
            raise ValueError(
                "--input_service=on requires all workers on one host "
                "(one shared-memory ring set per host); multi-host runs "
                "start one service per host via their own local launch")
        return True
    return world > 1 and layout.num_hosts == 1


class _ArrivalFetcher:
    """Background thread that serially fetches result handles and stamps
    their arrival wall time.

    This is the tunnel-safe timing mechanism: on remote-device bridges
    (axon) both ``block_until_ready`` and ``is_ready`` turn advisory once
    the dispatch queue is deep, so the only trustworthy completion signal
    is a value fetch — which costs a full RTT.  Fetching from a side
    thread keeps the RTT out of the dispatch path, and because every
    arrival is late by the same constant RTT, arrival-time *deltas*
    measure true device progress.

    When markers complete faster than one RTT the fetch queue would back
    up and the deltas would measure fetch serialization instead, so the
    thread *coalesces*: whenever several markers are already queued it
    timing-fetches only the newest and parks the rest in ``skipped``
    (values still wanted after the run are fetched then, when everything
    is complete and fetches are cheap).  The enqueue loop uses
    ``fetched_step`` for flow control (bounding in-flight steps).

    ``keep_value``: which parked steps' VALUES matter later (the display
    steps).  With every-step markers a long run coalesces over most of
    them; holding O(num_batches) device scalars alive for the whole run
    — and bulk-fetching them at the end — for values nobody reads would
    be allocator pressure for nothing, so coalesced-over markers outside
    the predicate park as ``(step, None)``.
    """

    def __init__(self, keep_value=None):
        import queue
        import threading

        self._q: queue.Queue = queue.Queue()
        self.arrivals: list[tuple[int, float, object]] = []
        self.skipped: list[tuple[int, object]] = []   # coalesced-over markers
        self._keep_value = keep_value or (lambda i: True)
        self.fetched_step = 0
        self.last_arrival_t: float | None = None   # watchdog progress oracle
        self._last_mono: float | None = None       # device_step span anchor
        self.error: BaseException | None = None
        self._error_tb = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def put(self, step_idx: int, handle) -> None:
        self.check()
        self._q.put((step_idx, handle))

    def check(self) -> None:
        """Re-raise a fetch error (XlaRuntimeError, OOM…) in the caller,
        with the ORIGINAL fetch-thread traceback attached — the step loop
        fails with the real error, not a context-free re-raise."""
        if self.error is not None:
            exc = self.error
            if hasattr(exc, "add_note") and not getattr(
                    exc, "_tpu_hc_noted", False):
                exc.add_note(
                    "raised in the arrival-fetch thread; re-raised in the "
                    "step loop (tpu_hc_bench.train.driver._ArrivalFetcher)")
                exc._tpu_hc_noted = True
            raise exc.with_traceback(self._error_tb)

    def _run(self) -> None:
        import queue as queue_mod

        while True:
            item = self._q.get()
            if item is None:
                return
            while True:         # coalesce everything already queued
                try:
                    nxt = self._q.get_nowait()
                except queue_mod.Empty:
                    break
                if nxt is None:
                    self._q.put(None)   # re-arm sentinel for the outer loop
                    break
                i0, h0 = item
                self.skipped.append(
                    (i0, h0 if self._keep_value(i0) else None))
                item = nxt
            i, h = item
            try:
                v = jax.device_get(h)
            except BaseException as e:   # surface in main thread, don't hang
                self.error = e
                self._error_tb = e.__traceback__
                self.fetched_step = 1 << 60   # unblock flow-control spins
                return
            self.arrivals.append((i, time.perf_counter(), v))
            self.last_arrival_t = time.perf_counter()
            # flight recorder (obs.timeline): the interval between
            # consecutive completion markers IS the device's view of the
            # step — recorded from this thread so the dispatch path pays
            # nothing
            m_now = time.monotonic()
            if self._last_mono is not None:
                timeline_mod.record_span("device_step", self._last_mono,
                                         m_now, step=i)
            self._last_mono = m_now
            self.fetched_step = i

    def finish(self) -> list[tuple[int, float, object]]:
        self._q.put(None)
        self._thread.join()
        self.check()
        return self.arrivals


class _AsyncTimeline:
    """The measurement protocol shared by the train and eval loops.

    Wraps an _ArrivalFetcher with the marker cadence (sync/display
    points), HBM flow control, and the post-run reconstruction of the
    windowed timeline.  Display steps that were coalesced over inherit
    the mean rate of the enclosing timed span; the final step is always
    timed (it is the newest marker when the queue drains), so the total
    is exact.
    """

    def __init__(self, num_batches: int, display_every: int,
                 global_batch: int):
        self.num_batches = num_batches
        self.display_every = display_every
        self.global_batch = global_batch
        # only display steps' VALUES are ever read back (the loss column);
        # coalesced-over markers elsewhere may drop their handles
        self.fetcher = _ArrivalFetcher(
            keep_value=lambda i: (i % display_every == 0
                                  or i == num_batches or i == 0))
        self.sync_every = max(1, min(display_every, 16))
        # flow-control bound on in-flight steps, so real-data runs don't
        # stack an unbounded queue of host->device batch transfers in HBM
        self.max_inflight = max(32, 2 * self.sync_every)
        # populated by finish(): timed per-step intervals + their width
        self.per_step_times: list[tuple[float, int]] = []
        self.p50_granularity = 1

    def start(self, handle) -> None:
        """Stamp t=0 with an already-fetched (cheap) marker handle.

        Blocks until the marker's arrival is recorded — otherwise a fast
        first window could coalesce over it and the timeline would lose
        its origin."""
        self.fetcher.put(0, handle)
        while not self.fetcher.arrivals:
            self.fetcher.check()
            time.sleep(1e-4)

    def record(self, i: int, handle) -> None:
        """Per-iteration bookkeeping: marker puts + flow control.

        EVERY step enqueues a marker (round 7; previously only
        sync/display points did): the fetch thread coalesces whenever it
        falls behind, so per-step completion times are recorded exactly
        as finely as the platform can truly observe them — on a fast
        local device that is every single step (true per-step p50), on a
        tunnel whose RTT exceeds the step time the arrivals thin out to
        multi-step intervals and ``p50_granularity`` reports the width
        honestly.
        """
        self.fetcher.put(i, handle)
        while i - self.fetcher.fetched_step > self.max_inflight:
            time.sleep(2e-3)
        self.fetcher.check()

    def finish(self, line_fn) -> float:
        """Drain; call ``line_fn(step, rate, value)`` per display step in
        order; return the total timed-span seconds.

        Also populates ``per_step_times`` (list of ``(dt_seconds,
        steps_spanned)`` per timed interval) and ``p50_granularity``
        (the width of the weighted-median interval; 1 = the reported
        p50 is a true per-step time) — see ``p50_step_ms``.
        """
        arrivals = self.fetcher.finish()
        values = {i: v for i, _, v in arrivals}
        # coalesced-over display markers: everything is complete now, so
        # the value fetches are cheap (non-display parks carry no handle)
        kept = [(i, h) for i, h in self.fetcher.skipped if h is not None]
        if kept:
            got = jax.device_get([h for _, h in kept])
            values.update({i: v for (i, _), v in zip(kept, got)})
        timed = {i: t for i, t, _ in arrivals}
        t0 = arrivals[0][1]
        total_time = arrivals[-1][1] - t0
        pts = sorted(timed.items())
        self.per_step_times = [
            (max((t1 - t0_) / (i1 - i0), 1e-9), i1 - i0)
            for (i0, t0_), (i1, t1) in zip(pts, pts[1:])
        ]
        # granularity = the width of the interval the reported median
        # comes from (NOT the max width: one transient coalesce in an
        # otherwise per-step run must not relabel the whole measurement)
        med = self._median_interval()
        self.p50_granularity = med[1] if med else 1
        prev_i, prev_t = 0, t0
        pending: list[int] = []
        for i in range(1, self.num_batches + 1):
            if not (i % self.display_every == 0 or i == self.num_batches):
                continue
            pending.append(i)
            if i in timed:
                dt = max((timed[i] - prev_t) / (i - prev_i), 1e-9)
                for j in pending:
                    line_fn(j, self.global_batch / dt, values.get(j))
                prev_i, prev_t = i, timed[i]
                pending = []
        return total_time

    def _median_interval(self) -> tuple[float, int] | None:
        """The weighted-median ``(dt_seconds, width)`` interval — each
        interval's per-step time weighted by the steps it spans, so a
        coalesced-over stretch counts as many steps, not one sample.
        The ONE home of the median rule: the reported p50 value and its
        granularity label both come from this pair."""
        samples = sorted(self.per_step_times)
        total = sum(w for _, w in samples)
        acc = 0
        for dt, w in samples:
            acc += w
            if 2 * acc >= total:
                return dt, w
        return None

    def step_sketch(self):
        """The timed intervals as a weighted quantile sketch (ms per
        step, weighted by steps spanned) — mergeable across ranks and
        the round-24 home of the reported p50.  None before finish()."""
        from tpu_hc_bench.obs import sketch as sketch_mod

        if not self.per_step_times:
            return None
        sk = sketch_mod.QuantileSketch()
        for dt, w in self.per_step_times:
            sk.add(1e3 * dt, w)
        return sk

    def p50_step_ms(self) -> float:
        sk = self.step_sketch()
        return sk.quantile(50) if sk is not None else float("nan")


class _TraceWindow:
    """Flag-driven windowed ``jax.profiler`` tracing with ONE stop path.

    ``--profile_steps=a:b`` selects the timed steps to profile into
    ``--trace_dir``; without it, ``--trace_dir`` keeps its legacy
    first-sync-window behavior (expressed as the window
    ``1:sync_every``).  The window is observed through the timeline's
    completion markers: the trace starts once every step before ``a``
    has *completed* (so the window isn't polluted by the in-flight tail
    of earlier steps) and stops once step ``b`` has completed.

    ``stop()`` is idempotent and is the only place the profiler is ever
    stopped — previously the timed loop's early exit and the post-loop
    cleanup each called ``jax.profiler.stop_trace`` behind their own
    flag, and a run ending inside the profiled window could stop twice.
    """

    def __init__(self, cfg: BenchmarkConfig, print_fn, sync_every: int):
        self.trace_dir = cfg.trace_dir
        self.print_fn = print_fn
        self.active = False
        self.started = False
        if cfg.profile_steps:
            self.start_step, self.stop_after = flags_mod.parse_profile_steps(
                cfg.profile_steps)
        else:
            self.start_step, self.stop_after = 1, sync_every

    def maybe_start(self, next_step: int, fetcher: _ArrivalFetcher) -> None:
        """Start the trace when the loop is about to dispatch
        ``next_step == a``; for a > 1, first wait for step a-1's
        completion marker so the window starts quiesced."""
        if (self.trace_dir is None or self.started
                or next_step < self.start_step):
            return
        if self.start_step > 1:
            while fetcher.fetched_step < self.start_step - 1:
                fetcher.check()
                time.sleep(1e-3)
        jax.profiler.start_trace(self.trace_dir)
        self.active = True
        self.started = True

    def poll(self, fetched_step: int) -> None:
        if self.active and fetched_step >= self.stop_after:
            self.stop()

    def stop(self) -> None:
        if not self.active:
            return
        jax.profiler.stop_trace()
        self.active = False
        self.print_fn(f"profiler trace written to {self.trace_dir}")

    def post_summary(self):
        """Print the bucket attribution of the trace just written
        (through the shared ``obs.trace`` formatter) and return the
        ``TraceSummary``, or None when no usable trace exists (e.g. a
        CPU run: the profiler writes host tracks only)."""
        if self.trace_dir is not None and not self.started:
            # the user asked for a trace and never got one — say so
            # instead of silently writing nothing (a --profile_steps
            # window starting past the run's end)
            self.print_fn(
                f"WARNING: profile window {self.start_step}:"
                f"{self.stop_after} never started (run ended first); "
                f"no trace written to {self.trace_dir}")
        if not self.started:
            return None
        try:
            from tpu_hc_bench.obs import trace as obs_trace

            summary = obs_trace.summarize_trace_dir(self.trace_dir)
        except Exception as e:   # degraded summary must not kill a run
            self.print_fn(f"trace summary unavailable: {e}")
            return None
        for line in obs_trace.format_summary(summary):
            self.print_fn(line)
        return summary


def _fingerprint_line(params, print_fn) -> None:
    """Best-effort params digest: emergency save and resume restore both
    print it, so kill/resume tests assert bitwise identity from the log.
    Silent when the state is not fully addressable (multi-host sharded)."""
    from tpu_hc_bench.utils import checkpoint as ckpt

    try:
        print_fn(f"params fingerprint: {ckpt.fingerprint(params)}")
    except Exception:
        pass


def _maybe_restore(state, cfg, print_fn, sharded=False, topo=None,
                   obs_writer=None):
    """--train_dir resume: restore the latest COMPLETE checkpoint, per
    the ``--resume`` policy (auto = restore if one exists, never = fresh
    init, must/elastic = error when none — a crash-looping relaunch must
    not silently restart from step 0).

    Returns ``(state, restored?, resume_record)``.  Default mode
    restores host arrays (the caller re-places them on the mesh);
    ``sharded=True`` takes an already-PLACED template and restores each
    array with its committed sharding, every process reading only its
    addressable shards (the multi-host model-sharded path).

    ``topo``: the LIVE topology record.  A checkpoint whose sidecar
    disagrees is validated through ``topology.elastic_plan`` — a loud
    :class:`utils.checkpoint.TopologyMismatchError` replaces the old
    opaque Orbax sharding error, ``--resume=elastic`` routes zero1
    states through the resplit path, and a one-line plan of what is
    being reshaped is printed.  The resume record (restored step, saved
    vs live world, arm) is also emitted into the metrics stream.
    """
    if not cfg.train_dir or cfg.resume == "never":
        return state, False, None
    from pathlib import Path

    from tpu_hc_bench.utils import checkpoint as ckpt

    if ckpt.latest_step(cfg.train_dir) is None:
        orphans = [p.name for p in Path(cfg.train_dir).glob("step_*")
                   if p.is_dir() and not p.name.endswith(".tmp")]
        if orphans:
            # crashed saves — or checkpoints from before the commit-
            # sentinel scheme.  Never restore them silently, but never
            # silently restart from step 0 over them either.
            print_fn(
                f"WARNING: {cfg.train_dir} has step dir(s) without a "
                f"commit sentinel ({', '.join(sorted(orphans)[:4])}"
                f"{'...' if len(orphans) > 4 else ''}): crashed saves, "
                f"or pre-sentinel checkpoints — verify and `touch "
                f"<dir>/step_NNNNNNNN.complete` to adopt; starting "
                f"fresh")
        if cfg.resume in ("must", "elastic"):
            raise FileNotFoundError(
                f"--resume={cfg.resume}: no complete checkpoint under "
                f"{cfg.train_dir}")
        return state, False, None
    saved_topo = ckpt.read_topology(cfg.train_dir)
    action, plan = "ok", ""
    if topo is not None and saved_topo is not None:
        # one loud line + error instead of an opaque Orbax shape error:
        # check_topology raises unless the transition is a no-op or an
        # elastic reshard the operator asked for
        action, plan = ckpt.check_topology(
            saved_topo, topo, cfg.train_dir,
            elastic=cfg.resume == "elastic")
        if plan:
            print_fn(f"elastic resume: {plan}")
    elif cfg.resume == "elastic" and saved_topo is None:
        print_fn("elastic resume: checkpoint has no topology sidecar "
                 "(pre-elastic save); assuming the saved topology "
                 "matches the live one")
    if action == "reshard" and not sharded:
        state = ckpt.restore_elastic(state, cfg.train_dir, saved_topo,
                                     topo["world"])
    else:
        state = ckpt.restore(state, cfg.train_dir, sharded=sharded)
    restored_step = int(jax.device_get(state.step))
    print_fn(f"restored checkpoint step {restored_step} from "
             f"{cfg.train_dir}")
    if not sharded:
        _fingerprint_line(state.params, print_fn)
    rec = None
    if saved_topo is not None or topo is not None:
        rec = {"restored_step": restored_step,
               "saved_world": (saved_topo or {}).get("world"),
               "live_world": (topo or {}).get("world"),
               "arm": (saved_topo or {}).get("variable_update"),
               "elastic": action == "reshard"}
        if obs_writer is not None:
            obs_writer.event("resume", **rec, saved_topology=saved_topo,
                             live_topology=topo, plan=plan or None)
    return state, True, rec


def _save_state(state, cfg, print_fn, pp_ctx=None, sharded=False,
                topology=None):
    """Save to --train_dir.  ``state`` is a TrainState, or the PP
    ``(params, opt_state)`` tuple when ``pp_ctx=(model, template)`` — the
    DP<->DPxPP checkpoint interchange: PP runs restack into the DP layout
    so the checkpoint restores under either strategy.  ``topology`` is
    the run's sidecar record (``topology.topology_record``), committed
    next to the step sentinel for elastic resume.

    Multi-process: ALL processes call (Orbax synchronizes internally and
    the primary host writes the replicated arrays); the driver guard has
    already ensured the state is replicated, not model-sharded."""
    if not cfg.train_dir:
        return
    from tpu_hc_bench.utils import checkpoint as ckpt

    if pp_ctx is not None:
        from tpu_hc_bench.parallel import pipeline as pipe_mod

        model, template, steps_done = pp_ctx
        params, opt_state = state
        state = pipe_mod.train_state_from_pp(
            params, opt_state, template, model.num_layers)
        state = state.replace(
            step=jax.numpy.asarray(steps_done, jax.numpy.int32))
    path = ckpt.save(state, cfg.train_dir, sharded=sharded,
                     topology=topology)
    print_fn(f"checkpoint saved: {path}")


_RANDOM_INIT_EVAL_WARNING = (
    "WARNING: --eval without --train_dir measures RANDOMLY INITIALIZED "
    "params — the accuracy line is meaningless; train with --train_dir "
    "first and pass it here")


def _require_checkpoint_for_eval(cfg, restored: bool, print_fn) -> None:
    """The one home of the eval-restore policy (all eval arms): a named
    --train_dir with no checkpoint is an error; no --train_dir at all
    warns that random init is being measured."""
    if restored:
        return
    if cfg.train_dir:
        raise FileNotFoundError(
            f"--eval: no checkpoint found under {cfg.train_dir}")
    print_fn(_RANDOM_INIT_EVAL_WARNING)


def _run_eval(cfg, spec, layout, mesh, state, batch_iter, global_batch,
              fab, print_fn, follow_inputs=False, eval_step=None,
              sp=False, dcn=False, tp=False, obs_writer=None):
    """tf_cnn_benchmarks --eval: timed forward passes + top-1 accuracy.

    ``follow_inputs=True``: TP/EP eval — the state enters model-sharded
    and the GSPMD eval step follows its committed shardings.
    ``sp=True``: the (data, seq) shard_map eval arm (``tp=True`` for the
    DP x SP x TP hybrid's partial-manual variant).
    ``dcn=True``: the multislice (dcn, data) eval arm.
    ``eval_step``: pre-built override (the PP eval step) with the same
    ``(state, batch) -> (loss, correct)`` contract."""
    from tpu_hc_bench.train import step as step_mod

    if eval_step is None:
        eval_step = step_mod.build_eval_step(mesh, cfg, spec,
                                             follow_inputs=follow_inputs,
                                             sp=sp, dcn=dcn, tp=tp)
    units = _example_units(cfg, spec)
    for _ in range(max(1, min(cfg.num_warmup_batches, 5))):
        loss, correct = eval_step(state, next(batch_iter))
    drain(loss)

    # async dispatch with the shared tunnel-safe protocol (_AsyncTimeline);
    # per-step correct counts are fetched in one transfer at the end
    corrects = []
    timeline = _AsyncTimeline(cfg.num_batches, cfg.display_every,
                              global_batch)
    timeline.start(loss)        # drained above: arrival stamps t=0
    for i in range(1, cfg.num_batches + 1):
        t_dw = time.monotonic()
        batch = next(batch_iter)
        t_disp = time.monotonic()
        timeline_mod.record_span("input_wait", t_dw, t_disp, step=i)
        loss, correct = eval_step(state, batch)
        timeline_mod.record_span("eval_dispatch", t_disp,
                                 time.monotonic(), step=i)
        corrects.append(correct)
        timeline.record(i, loss)
    display_recs: list[tuple[int, float, object]] = []
    total_time = timeline.finish(
        lambda i, rate, v: display_recs.append((i, rate, v)))
    obs_writer = obs_writer or obs_metrics.MetricsWriter(None)
    correct_np = np.asarray(jax.device_get(corrects))
    loss_vals = []
    for i, rate, v in display_recs:
        top1 = float(correct_np[:i].sum()) / (i * global_batch)
        loss_vals.append(float(np.asarray(v)))
        print_fn(f"{i}\ttop_1: {top1:.4f}\tloss: {loss_vals[-1]:.3f}")
        obs_writer.event("window", step=i, rate=rate,
                         step_ms=1e3 * global_batch / rate, top_1=top1,
                         loss=loss_vals[-1])
    correct_total = float(correct_np.sum())
    seen = cfg.num_batches * global_batch
    total_rate = cfg.num_batches * global_batch / total_time
    per_chip = total_rate / layout.total_workers
    peak = hw.peak_flops(dtype=cfg.compute_dtype)
    result = BenchmarkResult(
        model=cfg.model,
        total_workers=layout.total_workers,
        global_batch=global_batch,
        total_images_per_sec=total_rate,
        images_per_sec_per_chip=per_chip,
        mean_step_ms=1e3 * total_time / cfg.num_batches,
        p50_step_ms=timeline.p50_step_ms(),
        p50_step_granularity=timeline.p50_granularity,
        mfu=(spec.flops_per_example * per_chip) / peak,
        final_loss=float(loss_vals[-1]),
        fabric=fab.value,
    )
    print_fn("-" * 40)
    print_fn(f"eval top_1 accuracy: {correct_total / seen:.4f}")
    print_fn(f"total {units}/sec: {total_rate:.2f}")
    # one end-of-run memory sample (cheap, post-timing): the forward
    # pass's high water, capability-gated with the live-arrays fallback
    mem_ledger = obs_memory.MemoryLedger()
    obs_writer.event("memory",
                     **mem_ledger.sample("step", step=cfg.num_batches))
    result.peak_hbm_bytes = mem_ledger.peak_bytes or None
    result.hbm_bytes_limit = mem_ledger.bytes_limit
    result.mem_source = mem_ledger.source
    step_sk = timeline.step_sketch()
    if step_sk is not None:
        obs_writer.event("latency_sketch", window=0,
                         fields={"step_ms": step_sk.to_record()})
    obs_writer.event("summary", eval_top_1=correct_total / seen,
                     **result.json_line())
    obs_writer.close()
    timeline_mod.detach()
    return result


def run_benchmark(
    cfg: BenchmarkConfig,
    layout: Layout | None = None,
    fabric_name: str = "ici",
    print_fn: Callable[[str], None] = print,
    model_dtype=None,
) -> BenchmarkResult:
    """Run the full benchmark protocol; returns the measured result."""
    import jax.numpy as jnp

    fab = fabric_mod.resolve_fabric(fabric_name)
    # load the fabric-ceiling sweep NOW, loudly: a typo'd path must die
    # before warmup, not after the full run when the summary needs it
    fabric_ceiling = (obs_efficiency.load_fabric_ceiling(cfg.fabric_ceiling)
                      if cfg.fabric_ceiling else None)
    # --hbm_budget: parse loudly now; "auto" resolves to the device's
    # measured bytes_limit right before the pre-warmup AOT check
    hbm_budget = obs_memory.parse_hbm_budget(cfg.hbm_budget)
    # persistent compile cache (--compile_cache): activated before
    # anything lowers, so the warmup's compiles hit (warm start) or
    # populate (cold start) it; hit/miss is measured over the warmup
    # and recorded in the manifest
    compile_cache_dir = _resolve_compile_cache(cfg, print_fn)
    cache_entries_before = (_cache_entry_count(compile_cache_dir)
                            if compile_cache_dir else 0)
    layout = layout or discover_layout()
    # TP/EP claim the mesh's "model" axis, PP "pipe", SP "seq".  Round 2:
    # minor axes COMPOSE — DPxPPxTP and DPxSPxTP are the supported 3-D
    # hybrids (PP/SP manual shard_map axes, model auto/GSPMD); the other
    # pairings are rejected explicitly.
    pp = max(1, getattr(cfg, "pipeline_parallel", 1))
    sp = max(1, getattr(cfg, "sequence_parallel", 1))
    # degenerate SP (round 3): a seq-sharded attention impl at
    # sequence_parallel=1 runs on a size-1 seq axis (world-1 collectives)
    sp_active = sp > 1 or cfg.attention_impl in (
        "ring", "ulysses", "ulysses_flash")
    tp = max(1, cfg.model_parallel)
    ep = max(1, getattr(cfg, "expert_parallel", 1))
    if tp > 1 and ep > 1:
        raise ValueError(
            "--model_parallel and --expert_parallel share the mesh's "
            "model axis; pick one")
    if getattr(cfg, "scan_layers", False) and (pp > 1 or tp > 1 or ep > 1):
        raise ValueError(
            "--scan_layers stacks the trunk params [L, ...] (one compiled "
            "layer body), which the layer_i-based PP interface and the "
            "per-tensor TP/EP sharding rules do not address yet; drop "
            "--scan_layers or the model/pipe axes")
    if pp > 1 and sp > 1:
        raise ValueError(
            "--pipeline_parallel x --sequence_parallel is not a supported "
            "composition (supported hybrids: DPxPPxTP, DPxSPxTP)")
    if ep > 1 and (pp > 1 or sp > 1):
        raise ValueError(
            "--expert_parallel composes with data parallelism only")
    mp = max(tp, ep) * pp * sp      # minor product = DP-degree divisor
    sharded_ckpt = False
    pp_native_ckpt = False
    if cfg.train_dir and jax.process_count() > 1:
        # Plain-DP/SP state is REPLICATED (every host holds full copies:
        # process 0's device_get-and-save works, every process restores
        # from the shared filesystem).  TP/EP states — including the
        # DP x SP x TP hybrid's — are model-SHARDED: they save/restore
        # through Orbax's per-shard jax.Array I/O with every process
        # participating (utils.checkpoint sharded=True, restore AFTER
        # placement).  Multi-host PP (round 4) saves the PP-NATIVE
        # stacked layout (utils.checkpoint.save_pp): the DP-layout
        # interchange needs full addressability, which a pipe-sharded
        # trunk is not, so the checkpoint keeps the [L, ...] layout and
        # every process writes its shards.
        if pp > 1:
            pp_native_ckpt = True
            print_fn(
                "--train_dir multi-process PP: PP-native sharded Orbax "
                "(stacked [L,...] trunk; not interchangeable with "
                "DP-layout checkpoints); restore requires a filesystem "
                "shared by all hosts")
        else:
            # zero1's optimizer state is sharded over the data axis: at
            # world > 1 the shards span hosts and the host-gather save
            # path cannot address them — the sharded Orbax path (restore
            # AFTER placement) handles it like the TP/EP states
            sharded_ckpt = (max(tp, ep) > 1
                            or cfg.variable_update == "zero1")
            print_fn(
                "--train_dir multi-process: "
                + ("sharded Orbax I/O, every process writes its shards"
                   if sharded_ckpt else "process 0 writes")
                + "; restore requires a filesystem shared by all hosts")
    if layout.total_workers % mp:
        raise ValueError(
            f"--model_parallel/--expert_parallel/--pipeline_parallel/"
            f"--sequence_parallel product {mp} does not divide "
            f"{layout.total_workers} workers"
        )
    if (mp > 1 or sp_active) and fab is fabric_mod.Fabric.HOST:
        raise ValueError(
            "--model_parallel/--expert_parallel/--pipeline_parallel/"
            "--sequence_parallel (incl. the degenerate seq axis of the "
            "seq-sharded attention impls) requires a device fabric "
            "(ici/dcn): the host path's shard_map binds no seq axis and "
            "would silently re-replicate the shards"
        )
    if (cfg.on_nonfinite in ("skip", "rewind")
            and fab is fabric_mod.Fabric.HOST):
        # flags.resolve rejects the other unsupported arms; the fabric is
        # only known here
        raise ValueError(
            "--on_nonfinite=skip/rewind needs a compiled step (fabric "
            "ici/dcn): the host-fabric numpy step carries no in-step "
            "guard")
    # fabric=dcn selects the MULTISLICE layout: slices x hosts/slice x
    # chips, a leading `dcn` mesh axis splitting the data dimension so the
    # gradient allreduce's cross-slice phase is explicit (the reference's
    # second-transport-stack role, run-tf-sing-libfabric-intelmpi.sh:86-105).
    # Default: one slice per host (hosts without shared ICI); override
    # with --num_slices for multi-host slices.
    num_slices = 1
    if fab is fabric_mod.Fabric.DCN:
        num_slices = getattr(cfg, "num_slices", 0) or layout.num_hosts
        if num_slices > 1 and mp > 1:
            raise ValueError(
                "fabric=dcn multislice currently composes with data "
                "parallelism only")
    elif getattr(cfg, "num_slices", 0) > 1:
        raise ValueError("--num_slices requires fabric=dcn")
    mesh = build_mesh(layout, model_parallel=max(tp, ep),
                      pipeline_parallel=pp, sequence_parallel=sp,
                      num_slices=num_slices, force_seq_axis=sp_active)
    # with TP/EP/PP/SP, the data-parallel degree (and so the global batch
    # at fixed per-worker batch) shrinks by the minor-axis product
    global_batch = layout.global_batch(cfg.batch_size) // mp

    # elastic-resume topology record (round 12): world/mesh/arm/layout/
    # dtype identity, written as a sidecar next to every checkpoint's
    # commit sentinel and validated at restore — the thing that lets a
    # preempted 8-way run continue on the 4 chips that survive
    topo_rec = topology_record(
        layout=layout, mesh=mesh, cfg=cfg,
        layout_kind=("pp-native" if pp_native_ckpt
                     else "sharded" if sharded_ckpt else "host"))
    resume_rec: dict | None = None

    dtype = model_dtype or jnp.dtype(cfg.compute_dtype)
    model, spec = create_model(cfg.model, num_classes=cfg.num_classes,
                               dtype=dtype, attention_impl=cfg.attention_impl,
                               space_to_depth=cfg.use_space_to_depth,
                               fused_conv=getattr(cfg, "fused_conv", False),
                               seq_len=cfg.seq_len,
                               gradient_checkpointing=cfg.gradient_checkpointing,
                               moe_impl=getattr(cfg, "moe_impl", "einsum"),
                               rnn_impl=getattr(cfg, "rnn_impl", "hoisted"),
                               scan_layers=getattr(cfg, "scan_layers", False),
                               moe_capacity_factor=getattr(
                                   cfg, "moe_capacity_factor", 1.25),
                               moe_f_chunk=getattr(cfg, "moe_f_chunk", 0),
                               seq_axis=SEQ_AXIS if sp_active else None)
    if sp_active:
        seq_len = spec.input_shape[0]
        if seq_len % sp:
            raise ValueError(
                f"sequence length {seq_len} not divisible by "
                f"sequence_parallel={sp}")

    # real-data split, resolved ONCE: both the --num_epochs sizing and
    # the dataset construction below must read the same shards (eval
    # prefers a validation split when present, else falls back to train)
    if cfg.datasets_repeat_cached_sample and (
            cfg.data_dir is None or spec.is_text):
        # the flag isolates the DEVICE-side real-IMAGE step cost; synthetic
        # input is already host-free and the token path is ~wire-free
        # (16 KB/step — BASELINE.md real-text table), so accepting the flag
        # there would print a banner claiming an isolation that never ran
        raise ValueError(
            "--datasets_repeat_cached_sample needs a real image dataset "
            "(--data_dir with TFRecord shards); it is meaningless for "
            "synthetic input and unsupported for text corpora")
    if cfg.datasets_repeat_cached_sample and (cfg.eval or cfg.num_epochs):
        # same loud-error principle: an "epoch" sized for the full dataset
        # or a "validation accuracy" computed over 8 cycled batches would
        # wear a banner describing a measurement that never happened
        raise ValueError(
            "--datasets_repeat_cached_sample is a throughput-isolation "
            "mode (a handful of batches cycled forever); it cannot define "
            "an epoch (--num_epochs) or a split-wide metric (--eval)")

    data_split = None
    if cfg.data_dir is not None and not spec.is_text:
        from tpu_hc_bench.data.imagenet import find_shards

        data_split = "train"
        if cfg.eval:
            try:
                find_shards(cfg.data_dir, "validation")
                data_split = "validation"
            except FileNotFoundError:
                pass

    if cfg.num_epochs:
        # tf_cnn_benchmarks --num_epochs: duration in dataset passes,
        # resolvable only here (needs the global batch and the ACTUAL
        # dataset — synthetic/text streams have no epoch size, so they
        # reject rather than silently assume ilsvrc2012 splits).
        # num_epochs is cleared after derivation so cfg stays
        # re-resolvable.
        import math

        if data_split is None:
            raise ValueError(
                "--num_epochs needs a real image dataset (--data_dir): "
                "synthetic and text inputs are endless streams with no "
                "epoch size; use --num_batches")
        from tpu_hc_bench.data.imagenet import count_examples

        examples = count_examples(cfg.data_dir, data_split)
        cfg.num_batches = math.ceil(
            cfg.num_epochs * examples / global_batch)
        print_fn(f"num_epochs={cfg.num_epochs} ({examples} examples) -> "
                 f"num_batches={cfg.num_batches} "
                 f"(global_batch={global_batch})")
        cfg.num_epochs = 0.0

    # --- banner (reference :52-58 config echo) ---
    for line in layout.summary_lines(fabric=fab.value):
        print_fn(line)
    for line in cfg.summary_lines():
        print_fn(line)
    fcfg = fabric_mod.FabricConfig(fab, cfg.fusion_threshold_bytes)
    print_fn(fcfg.summary())
    if num_slices > 1:
        per_slice = (f"{layout.num_hosts // num_slices} host(s)/slice"
                     if num_slices <= layout.num_hosts
                     else f"virtual slices on {layout.num_hosts} host(s)")
        print_fn(
            f"multislice: {num_slices} slices x {per_slice} — data axis = "
            f"dcn({num_slices}) x data({layout.total_workers // num_slices})")
    print_fn(f"device_kind={hw.device_kind()} global_batch={global_batch}")
    if compile_cache_dir:
        print_fn(f"compile cache: {compile_cache_dir} "
                 f"({cache_entries_before} entries at start)")
    for line in hw.ici_topology_lines():
        print_fn(line)

    # --- run observability (obs.metrics): manifest eagerly, so even a
    # crashed run leaves its identity behind; worker 0 writes and is the
    # only one that even BUILDS the manifest (git subprocess + version
    # probes are wasted work on the N-1 processes whose writer no-ops) —
    # records are already globally aggregated (psum'd loss, global-batch
    # rates), so its view is the merged record
    if cfg.metrics_dir and jax.process_index() == 0:
        # checkpoint topology identity rides the manifest too, so `obs
        # diff` can name a world-size change across a resume boundary
        manifest_extra: dict = {"topology": topo_rec}
        if compile_cache_dir:
            manifest_extra["compile_cache"] = {
                "dir": compile_cache_dir,
                "entries_before": cache_entries_before}
        if cfg.variable_update == "zero1":
            # manifest-noted checkpoint policy: single-process zero1
            # saves gather the sharded optimizer state to host
            # (gather-on-save); multi-process uses sharded Orbax I/O.
            # No --train_dir = no checkpoints = no policy to note.
            zrec: dict = {"opt_state_sharded": True,
                          "opt_shards": layout.total_workers}
            if cfg.train_dir:
                zrec["checkpoint"] = ("sharded" if sharded_ckpt
                                      else "gather-on-save")
            manifest_extra["zero1"] = zrec
        obs_writer = obs_metrics.MetricsWriter(
            cfg.metrics_dir,
            obs_metrics.run_manifest(
                cfg=cfg, layout=layout, mesh=mesh, fabric=fab.value,
                extra=manifest_extra or None),
            primary=True)
        print_fn(f"metrics: {cfg.metrics_dir}/{obs_metrics.METRICS_NAME} "
                 f"(+ {obs_metrics.MANIFEST_NAME}); live view: "
                 f"python -m tpu_hc_bench.obs watch {cfg.metrics_dir}")
    else:
        obs_writer = obs_metrics.MetricsWriter(None)
    # flight recorder (obs.timeline): always-on bounded span ring; with
    # --metrics_dir EVERY rank persists its spans.<k>.jsonl beside the
    # heartbeats (per-rank visibility, like FleetWriter).  Configured
    # BEFORE the phase tracker so the init transition lands in the ring.
    timeline_mod.configure(enabled=cfg.flight_recorder != "off",
                           run_dir=cfg.metrics_dir,
                           rank=jax.process_index())
    # goodput ledger (obs.goodput): phase transitions into the metrics
    # stream + a local mirror so the final account never re-reads the
    # file; enters "init" now
    phases = obs_goodput.PhaseTracker(obs_writer)

    # --- data ---
    input_svc = None        # rank-0's InputService (stats + shutdown)
    svc_client = None       # this worker's ring consumer
    if cfg.data_dir is not None and not spec.is_text:
        # real ImageNet TFRecords, per-host shard split (reference :19,80-81)
        from tpu_hc_bench.data.imagenet import ImageNetDataset

        image_size = spec.default_image_size
        # round 14: sliced input — each worker decodes and ships ONLY
        # its own rows of the global batch (the service rings carry the
        # slice, the per-process pipeline decodes just the consumed
        # rows), and jax.make_array_from_process_local_data assembles
        # the global array.  The pre-round-14 arm (every process builds
        # the FULL global batch, device_put keeps the local slice) is
        # the bitwise A/B control, kept as --full_batch_identity and as
        # the fallback on stacks without the API.  Delivered pixels are
        # identical either way (per-row RNG keying); only the W-fold
        # redundant host decode/copy disappears.
        in_world = jax.process_count()
        sliced_input = False
        if in_world > 1 and not cfg.full_batch_identity:
            from tpu_hc_bench._compat import CAPABILITIES

            if not CAPABILITIES["process_local_arrays"]:
                print_fn("sliced input: this jax lacks "
                         "make_array_from_process_local_data — "
                         "full-batch identity fallback")
            elif global_batch % in_world:
                print_fn(f"sliced input: global batch {global_batch} "
                         f"not divisible by {in_world} worker(s) — "
                         "full-batch identity fallback")
            else:
                sliced_input = True
        _rows = None
        if sliced_input:
            per_w = global_batch // in_world
            _rows = (jax.process_index() * per_w,
                     (jax.process_index() + 1) * per_w)
        if _input_service_on(cfg, layout):
            # host-level shared input service (round 13): the lowest
            # local rank owns ONE decode pool and feeds every local
            # worker's shm ring; each worker's delivered stream is
            # bitwise-identical to the per-process pipeline it replaces
            from tpu_hc_bench.data import service as service_mod

            world = jax.process_count()
            ring_depth = max(2, cfg.prefetch_depth)
            # every rank must derive the SAME name; a per-launch nonce
            # broadcast from rank 0 keeps (a) a relaunch from attaching
            # to a crashed run's stale segment before rank 0 reclaims
            # it and (b) concurrent same-config runs on one host apart.
            # Falls back to a config-only name if the collective is
            # unavailable (then the config-hash + stale-reclaim in
            # ShmRing.create is the only guard).
            nonce = os.getpid()
            if world > 1:
                try:
                    from jax.experimental import multihost_utils

                    nonce = int(multihost_utils.broadcast_one_to_all(
                        np.int64(os.getpid() * 1000
                                 + (time.monotonic_ns() // 1000) % 1000)))
                except Exception:
                    nonce = 0
            svc_name = service_mod.service_name(
                cfg.data_dir, data_split, cfg.seed, global_batch,
                image_size, cfg.wire_dtype, cfg.model,
                cfg.metrics_dir or "", cfg.train_dir or "",
                "sliced" if sliced_input else "full", nonce)
            if jax.process_index() == 0:
                input_svc = service_mod.make_image_service(
                    [cfg.data_dir], num_workers=world,
                    global_batch=global_batch, image_size=image_size,
                    split=data_split, train=not cfg.eval, seed=cfg.seed,
                    wire_dtype=cfg.wire_dtype,
                    decode_workers=cfg.service_decode_workers,
                    depth=ring_depth, name=svc_name,
                    slice_per_worker=sliced_input,
                ).start()
                print_fn(
                    f"input service: host decode pool "
                    f"{input_svc.decode_workers} thread(s) serving "
                    f"{world} worker(s) over shared-memory rings "
                    f"(depth {ring_depth}"
                    + (", sliced rings: each worker's ring carries "
                       f"only its {global_batch // world} rows"
                       if sliced_input else "") + ")")
            # copy=True: the batch feeds an ASYNC jax.device_put (which
            # on CPU may even alias the aligned buffer) while _prefetch
            # pulls ahead — a zero-copy view's slot could be recycled
            # mid-transfer, so the client takes an owned copy per batch
            svc_client = service_mod.ServiceClient(
                svc_name,
                service_mod.image_batch_layout(
                    global_batch // world if sliced_input else global_batch,
                    image_size, cfg.wire_dtype),
                worker=jax.process_index(), depth=ring_depth, copy=True,
                # a dead service host must surface as an error, not an
                # eternal data wait (10 min covers any sane decode)
                stall_timeout_s=600.0)
            ds = svc_client
            host_iter = iter(svc_client)
        else:
            # ceil-divide on ragged layouts: over-dividing the pool is
            # safe, while a fall-back to 1 would reinstate the full-
            # width-per-process oversubscription this exists to fix
            local_workers = -(-jax.process_count() // layout.num_hosts)
            ds = ImageNetDataset(
                cfg.data_dir,
                global_batch=global_batch,
                image_size=image_size,
                split=data_split,
                train=not cfg.eval,
                worker=jax.process_index(),
                num_workers=jax.process_count(),
                seed=cfg.seed,
                # uint8 ships 4x less host->device traffic; the
                # cast+normalize runs inside the compiled step
                # (train.step.prep_inputs)
                wire_dtype=cfg.wire_dtype,
                # 0 = auto-size the decode pool to this worker's SHARE
                # of the host's cores (divided by local worker count —
                # N private pools must not claim N*(cpu-1) threads)
                decode_workers=cfg.datasets_num_private_threads,
                local_workers=local_workers,
                prefetch=cfg.prefetch_depth,
                # sliced mode: decode only the rows this process's
                # devices hold; the per-row RNG still advances over all
                # rows, so the delivered pixels are bitwise-identical
                # to the full pipeline's same rows
                decode_rows=_rows,
            )
            print_fn(f"decode pool: {ds.decode_workers} thread(s)/worker "
                     f"({local_workers} local worker(s) share "
                     f"{os.cpu_count()} host CPUs; per-process pipeline"
                     + (f"; sliced: decoding rows [{_rows[0]}, {_rows[1]})"
                        if _rows is not None else "") + ")")
            host_iter = iter(ds)
            if sliced_input:
                # decode_rows yields full-shaped batches with only the
                # local rows decoded — hand downstream just the rows.
                # close() must reach the dataset iterator (the
                # repeat_cached path stops the decode pool through it)
                def _local_rows(it, lo=_rows[0], hi=_rows[1]):
                    try:
                        for b in it:
                            yield tuple(a[lo:hi] for a in b)
                    finally:
                        it.close()
                host_iter = _local_rows(host_iter)
        batch = next(host_iter)
        # sliced mode ships local rows through make_array_from_process_
        # local_data; the identity arm ships the global batch through
        # device_put (which keeps the local slice)
        place_batch = (
            (lambda b: step_mod.shard_batch_local(b, mesh)) if sliced_input
            else (lambda b: step_mod.shard_batch(b, mesh)))

        if cfg.datasets_repeat_cached_sample:
            # --datasets_repeat_cached_sample: decode a handful of REAL
            # batches once, park them on device, cycle.  This takes the
            # host decode + tunnel transfer wall out of the loop so the
            # number measures the device-side real-data step (uint8 wire
            # cast + normalize run inside the compiled step —
            # train/step.py::prep_inputs), augmentation baked in at decode.
            # Stricter isolation than tf_cnn's mechanics (which repeat one
            # cached record through the LIVE pipeline and still pay the
            # per-step transfer) — see the deviation note in flags.py.
            # 8 distinct batches keep XLA from seeing a constant input
            # while staying far under HBM pressure at bench batch sizes.
            import itertools

            cached = [
                place_batch(b)
                for b in itertools.chain(
                    [batch], itertools.islice(host_iter, 7))
            ]
            # stop the decode pool NOW: a live producer thread polling the
            # prefetch queue is exactly the host work this flag exists to
            # take out of the measurement
            host_iter.close()
            print_fn(f"repeat_cached_sample: {len(cached)} real batches "
                     "decoded once, device-resident, cycled per step")

            def batches():
                yield from itertools.cycle(cached)
        else:
            def batches():
                def raw():
                    import itertools

                    for b in itertools.chain([batch], host_iter):
                        yield place_batch(b)
                yield from _prefetch(raw(), cfg.prefetch_depth)
    elif spec.is_text and cfg.data_dir is not None:
        # real pre-tokenized corpus (<data_dir>/<split>.bin memmap) — the
        # reference's real-data axis for the text members (round 3)
        from tpu_hc_bench.data.tokens import TokenDataset, _resolve
        from jax.sharding import PartitionSpec as P

        seq_len = spec.input_shape[0]
        split = "train"
        if cfg.eval:
            try:
                _resolve(cfg.data_dir, "validation")
                split = "validation"
            except FileNotFoundError:
                pass
        ds = TokenDataset(
            cfg.data_dir, global_batch, seq_len, split=split,
            causal_lm=spec.causal_lm,
            worker=jax.process_index(), num_workers=jax.process_count(),
            seed=cfg.seed, vocab_size=spec.vocab_size,
        )
        host_iter = iter(ds)
        batch = next(host_iter)
        batch_spec = P(DATA_AXIS, SEQ_AXIS) if sp_active else None

        def batches():
            def raw():
                import itertools

                for b in itertools.chain([batch], host_iter):
                    yield step_mod.shard_batch(b, mesh, batch_spec)
            yield from _prefetch(raw(), cfg.prefetch_depth)
    elif spec.is_text:
        seq_len = spec.input_shape[0]
        ds = SyntheticTokens(global_batch, seq_len, seed=cfg.seed,
                             vocab_size=spec.vocab_size,
                             causal_lm=spec.causal_lm)
        batch = ds.batch()
        from jax.sharding import PartitionSpec as P

        # under SP the [B, S] token batch shards over BOTH mesh axes
        batch_spec = P(DATA_AXIS, SEQ_AXIS) if sp_active else None

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh, batch_spec)
            while True:
                yield dev_batch
    elif getattr(spec, "ctc", False):
        # deepspeech2: spectrogram frames + padded CTC transcripts
        from tpu_hc_bench.data.synthetic import SyntheticSpeech
        from tpu_hc_bench.models.deepspeech import max_label_for

        if cfg.data_dir is not None:
            raise ValueError(
                f"--data_dir is not supported for {cfg.model} "
                "(synthetic spectrograms only)")
        if cfg.eval:
            raise ValueError(
                "--eval is not supported for the CTC member (decode/CER "
                "is outside the benchmark protocol)")
        frames, freq = spec.input_shape
        # CTC validity: label length bounded by the post-conv frame count
        ds = SyntheticSpeech(global_batch, frames, freq,
                             max_label_for(frames), seed=cfg.seed)
        batch = ds.batch()

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh)
            while True:
                yield dev_batch
    elif getattr(spec, "integer_input", False):
        # NCF: [B, 2] (user, item) id pairs + binary labels — same
        # fixed-batch contract as the image members
        from tpu_hc_bench.data.synthetic import SyntheticIds

        if cfg.data_dir is not None:
            raise ValueError(
                f"--data_dir is not supported for {cfg.model} "
                "(synthetic implicit-feedback pairs only)")
        m = model
        ds = SyntheticIds(global_batch, num_users=m.num_users,
                          num_items=m.num_items, seed=cfg.seed)
        batch = ds.batch()

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh)
            while True:
                yield dev_batch
    else:
        ds = SyntheticImages(
            global_batch, spec.input_shape, num_classes=cfg.num_classes,
            seed=cfg.seed,
        )
        batch = ds.batch()

        def batches():
            dev_batch = step_mod.shard_batch(batch, mesh)
            while True:
                yield dev_batch

    # --- state + step ---
    pp_save_ctx = None     # (model, template) when PP saves need restacking
    place_fn = None        # re-place a host-restored state on the mesh (the
                           # --on_nonfinite=rewind mid-run restore path)
    if sp_active:
        print_fn(f"sequence parallel: {sp} shards x "
                 f"{spec.input_shape[0] // sp} tokens/shard "
                 f"({cfg.attention_impl})")
        # init with the unsharded twin (identical params; axis_index needs
        # a bound mesh axis so the SP model itself can't init here), then
        # swap in the SP apply
        init_model = model.clone(attention_impl="dense", seq_axis=None)
        state = step_mod.make_train_state(init_model, cfg, batch)
        state = state.replace(apply_fn=model.apply)
        if not sharded_ckpt:
            state, sp_restored, resume_rec = _maybe_restore(
                state, cfg, print_fn, topo=topo_rec, obs_writer=obs_writer)
        if tp > 1:
            # DP x SP x TP: params/opt model-sharded (auto axis), the SP
            # step's shard_map stays manual over data+seq only
            print_fn(f"tensor parallel: {tp}-way (hybrid with SP)")
            place_fn = lambda s: step_mod.shard_state_tp(s, mesh)
        else:
            place_fn = lambda s: step_mod.replicate_state(s, mesh)
        state = place_fn(state)
        if sharded_ckpt:
            # multi-host SP x TP (round 4): same restore-after-placement
            # as the plain TP arm — Orbax reads each array straight into
            # its committed model sharding
            state, sp_restored, resume_rec = _maybe_restore(
                state, cfg, print_fn, sharded=True, topo=topo_rec,
                obs_writer=obs_writer)
        batch_iter = batches()
        if cfg.eval:
            # round 3: SP eval — the (data, seq) shard_map eval arm with
            # the shared text-metric formulas (exact global weighted
            # mean); round 4 extends it to the DP x SP x TP hybrid
            # (partial-manual, model axis auto), completing the eval
            # matrix (DP/TP/EP/PP/SP/hybrids)
            _require_checkpoint_for_eval(cfg, sp_restored, print_fn)
            return _run_eval(
                cfg, spec, layout, mesh, state, batch_iter, global_batch,
                fab, print_fn, sp=True, tp=tp > 1, obs_writer=obs_writer,
            )
        # the shared psum step builder handles SP (axes = (data, seq),
        # fusion buckets reduce over both)
        train_step = step_mod.build_train_step(mesh, cfg, spec, fab)
    elif pp > 1:
        # the PP step builder derives the stage forward from the model's
        # pp_embed/pp_layer_module/pp_head interface (GPT + llama
        # families); models without it (CNNs, encoder-only) can't pipeline
        if not all(hasattr(model, m) for m in
                   ("pp_embed", "pp_layer_module", "pp_head")):
            raise ValueError(
                "--pipeline_parallel requires a decoder implementing the "
                "PP interface (pp_embed/pp_layer_module/pp_head: the GPT "
                f"and llama families), not {cfg.model}")
        from tpu_hc_bench.parallel import pipeline as pipe_mod

        if model.num_layers % pp:
            raise ValueError(
                f"{cfg.model}: {model.num_layers} layers not divisible by "
                f"pipeline_parallel={pp}")
        num_mb = cfg.num_microbatches or (
            2 * pp if cfg.batch_size % (2 * pp) == 0 else pp)
        if cfg.batch_size % num_mb:
            raise ValueError(
                f"per-worker batch {cfg.batch_size} not divisible by "
                f"num_microbatches={num_mb}")
        print_fn(f"pipeline: {pp} stages x {num_mb} microbatches "
                 f"({model.num_layers // pp} layers/stage)")
        if tp > 1:
            print_fn(f"tensor parallel: {tp}-way (hybrid with PP)")
        pp_base_step = 0
        restored = False
        if pp_native_ckpt:
            # multi-host PP (round 4): PP-native sharded checkpoints —
            # init placed, then restore each array into its committed
            # pipe/model sharding (utils.checkpoint.restore_pp); saves go
            # through save_pp in save_now (no DP-layout interchange)
            from tpu_hc_bench.utils import checkpoint as ckpt_mod

            params, opt_state = pipe_mod.make_pp_state(model, cfg, batch[0],
                                                       mesh, tp=tp > 1)
            if (cfg.resume in ("must", "elastic")
                    and ckpt_mod.latest_step(cfg.train_dir) is None):
                raise FileNotFoundError(
                    f"--resume={cfg.resume}: no complete checkpoint "
                    f"under {cfg.train_dir}")
            if (cfg.resume != "never"
                    and ckpt_mod.latest_step(cfg.train_dir) is not None):
                saved_topo = ckpt_mod.read_topology(cfg.train_dir)
                if saved_topo is not None:
                    # pp-native stacked global shapes are pipe-degree
                    # independent and Orbax re-places them, so same-
                    # layout mesh changes validate as a no-op; cross-
                    # layout transitions refuse loudly here instead of
                    # dying in an Orbax structure mismatch
                    _, plan = ckpt_mod.check_topology(
                        saved_topo, topo_rec, cfg.train_dir,
                        elastic=cfg.resume == "elastic")
                    if plan:
                        print_fn(f"elastic resume: {plan}")
                if cfg.eval:
                    params, _, pp_base_step = ckpt_mod.restore_pp(
                        params, None, cfg.train_dir)
                    opt_state = None
                else:
                    params, opt_state, pp_base_step = ckpt_mod.restore_pp(
                        params, opt_state, cfg.train_dir)
                restored = True
                print_fn(f"restored checkpoint step {pp_base_step} from "
                         f"{cfg.train_dir} (PP-native)")
                if saved_topo is not None:
                    resume_rec = {
                        "restored_step": pp_base_step,
                        "saved_world": saved_topo.get("world"),
                        "live_world": topo_rec.get("world"),
                        "arm": saved_topo.get("variable_update"),
                        "elastic": False}
                    obs_writer.event("resume", **resume_rec,
                                     saved_topology=saved_topo,
                                     live_topology=topo_rec, plan=None)
            if cfg.eval:
                _require_checkpoint_for_eval(cfg, restored, print_fn)
        else:
            if cfg.train_dir:
                # DP<->DPxPP checkpoint interchange: restore the DP-layout
                # checkpoint through a host-side abstract template (no
                # device memory — PP models may not fit one device),
                # restack the layer subtrees into the pipe-sharded trunk,
                # re-place
                pp_template = step_mod.abstract_train_state(model, cfg,
                                                            batch)
                restored_t, restored, resume_rec = _maybe_restore(
                    pp_template, cfg, print_fn, topo=topo_rec,
                    obs_writer=obs_writer)
                if restored:
                    pp_base_step = int(np.asarray(restored_t.step))
                    if cfg.eval:
                        # forward-only: never restack or place the
                        # params-sized momentum trace (a PP model may not
                        # fit one device WITH it)
                        params = pipe_mod.stack_layer_params(
                            restored_t.params, model.num_layers)
                        params = pipe_mod.place_pp_state(
                            params, None, mesh, tp=tp > 1)
                        opt_state = None
                    else:
                        params, opt_state = \
                            pipe_mod.pp_state_from_train_state(
                                restored_t, model.num_layers)
                        params, opt_state = pipe_mod.place_pp_state(
                            params, opt_state, mesh, tp=tp > 1)
                pp_save_ctx = (model, pp_template, pp_base_step)
            if not restored:
                if cfg.eval:
                    _require_checkpoint_for_eval(cfg, restored, print_fn)
                params, opt_state = pipe_mod.make_pp_state(
                    model, cfg, batch[0], mesh, tp=tp > 1)
        if cfg.eval:
            # round 3: PP eval — forward-only pipeline (deterministic),
            # same loss/top-1 arms as DP eval of the same checkpoint
            pp_eval = pipe_mod.build_pp_eval_step(
                mesh, model, cfg, num_mb, params, tp=tp > 1)
            return _run_eval(
                cfg, spec, layout, mesh, params, batches(), global_batch,
                fab, print_fn, eval_step=pp_eval, obs_writer=obs_writer,
            )
        pp_step, _ = pipe_mod.build_pp_train_step(
            mesh, model, cfg, num_mb, params, opt_state, tp=tp > 1)
        state = (params, opt_state)

        def train_step(state, batch, rng):
            new_params, new_opt, loss = pp_step(*state, batch, rng)
            return (new_params, new_opt), {"loss": loss}

        batch_iter = batches()
    else:
        zero1 = cfg.variable_update == "zero1"
        if zero1:
            # the compositions flags.resolve can't see (fabric, slices)
            # die here, before any state is built
            if fab is fabric_mod.Fabric.HOST:
                raise ValueError(
                    "--variable_update=zero1 needs a device fabric "
                    "(ici): the host path has no sharded optimizer")
            if num_slices > 1:
                raise ValueError(
                    "--variable_update=zero1 composes with single-slice "
                    "data parallelism only (no multislice reduce-scatter "
                    "layout yet)")
            print_fn(
                f"zero1: optimizer state sharded {layout.total_workers}"
                f"-way over the data axis (reduce-scatter + sharded "
                f"update + all-gather; overlap_grad_comm="
                f"{cfg.overlap_grad_comm})")
            state = step_mod.make_zero1_state(model, cfg, batch,
                                              layout.total_workers)
        else:
            state = step_mod.make_train_state(model, cfg, batch)
        if not sharded_ckpt:
            state, restored, resume_rec = _maybe_restore(
                state, cfg, print_fn, topo=topo_rec, obs_writer=obs_writer)
        if mp > 1:
            mode = "ep" if getattr(cfg, "expert_parallel", 1) > 1 else "tp"
            place_fn = lambda s, m=mode: step_mod.shard_state_tp(s, mesh, m)
        elif zero1:
            place_fn = lambda s: step_mod.place_zero1_state(s, mesh)
        else:
            place_fn = lambda s: step_mod.replicate_state(s, mesh)
        state = place_fn(state)
        if sharded_ckpt:
            # multi-host TP/EP: restore AFTER placement so Orbax reads
            # each array straight into its committed sharding
            state, restored, resume_rec = _maybe_restore(
                state, cfg, print_fn, sharded=True, topo=topo_rec,
                obs_writer=obs_writer)
        if cfg.eval:
            _require_checkpoint_for_eval(cfg, restored, print_fn)
        batch_iter = batches()
        if cfg.eval:
            # round 4: dcn=True is the multislice eval arm — the same
            # (dcn, data) batch split + hierarchical metric psum as the
            # multislice train step, forward-only
            return _run_eval(
                cfg, spec, layout, mesh, state, batch_iter, global_batch,
                fab, print_fn, follow_inputs=mp > 1, dcn=num_slices > 1,
                obs_writer=obs_writer,
            )
        train_step = step_mod.build_train_step(mesh, cfg, spec, fab)
    rng = jax.random.PRNGKey(cfg.seed + 17)

    # per-host heartbeat stream (obs.fleet): EVERY process writes its
    # own metrics.<process_index>.jsonl — per-host visibility is the
    # point, so this is deliberately not primary-gated like the main
    # stream.  Train loop only (created after the eval arms return).
    fleet_writer = obs_fleet.FleetWriter(cfg.metrics_dir)
    # runtime HBM ledger (obs.memory): sampled once per sync window on
    # metrics runs, plus one end-of-run sample on every run
    mem_ledger = obs_memory.MemoryLedger()

    # --- warmup (includes compile; reference warmup=50, :32) ---
    # rng is folded with the step counter so dropout masks differ per step
    phases.enter("compile")
    t_compile = time.perf_counter()
    metrics = None
    warm_batch = next(batch_iter)
    flops_probe = None
    probe_wanted = bool(obs_writer.enabled or cfg.fabric_ceiling
                        or hbm_budget is not None)
    if hbm_budget is not None:
        # --hbm_budget: the AOT memory report must exist BEFORE the
        # warmup pays for the full run's compile, so the probe runs
        # SYNCHRONOUSLY here (its compiled handle also serves the MFU
        # probe — one compile, both measurements) and the verdict
        # prints at run start.
        flops_probe = obs_efficiency.StepFlopsProbe(
            train_step, state, warm_batch, rng, background=False)
        budget_bytes, budget_note = obs_memory.resolve_hbm_budget_bytes(
            hbm_budget)
        mem_an = flops_probe.memory_analysis()
        for ln in obs_memory.budget_lines(mem_an, budget_bytes,
                                          budget_note):
            print_fn(ln)
        if budget_bytes is not None and mem_an:
            obs_writer.event(
                "hbm_budget", budget_bytes=budget_bytes,
                total_bytes=mem_an.get("total_bytes", 0),
                exceeded=mem_an.get("total_bytes", 0) > budget_bytes)
    try:
        for w in range(max(1, cfg.num_warmup_batches)):
            if w:
                warm_batch = next(batch_iter)
            state, metrics = train_step(state, warm_batch,
                                        jax.random.fold_in(rng, w))
        drain(metrics["loss"])
    except BaseException as e:
        # OOM forensics: the warmup (first full materialization of the
        # step's activations) is where memory walls actually hit
        if obs_memory.is_oom_error(e) and cfg.metrics_dir:
            dpath = obs_memory.dump_forensics(
                cfg.metrics_dir, reason="oom", error=str(e),
                print_fn=print_fn)
            if dpath:
                obs_writer.event("memory_dump",
                                 path=os.path.basename(dpath),
                                 reason="oom")
            tpath = timeline_mod.dump_timeline(cfg.metrics_dir,
                                               reason="oom")
            if tpath:
                obs_writer.event("timeline_dump",
                                 path=os.path.basename(tpath),
                                 reason="oom")
        raise
    warmup_elapsed = time.perf_counter() - t_compile
    print_fn(
        f"warmup done: {cfg.num_warmup_batches} steps in "
        f"{warmup_elapsed:.1f}s (includes compile)"
    )
    if compile_cache_dir:
        # hit/miss accounting: entries that appeared during warmup are
        # the compiles this run actually paid for; zero new entries over
        # a non-empty cache is a warm start (the ledger's compile phase
        # shows the wall-clock consequence)
        cache_entries_after = _cache_entry_count(compile_cache_dir)
        cache_new = cache_entries_after - cache_entries_before
        cache_warm = cache_new == 0 and cache_entries_before > 0
        print_fn(f"compile cache: {cache_new} new entr"
                 f"{'y' if cache_new == 1 else 'ies'} "
                 f"({'warm start' if cache_warm else 'cold/partial'}); "
                 f"{cache_entries_after} total")
        cache_rec = {"dir": compile_cache_dir,
                     "entries_before": cache_entries_before,
                     "entries_after": cache_entries_after,
                     "new_entries": cache_new, "warm": cache_warm}
        obs_writer.event("compile_cache", **cache_rec)
        obs_writer.update_manifest({"compile_cache": cache_rec})

    # measured FLOPs (obs.efficiency): AOT-lower the very step program
    # and ask XLA's cost analysis — the honest MFU numerator.  Only on
    # observability-enabled runs: the extra compile is wasted wall on a
    # bare benchmark run.  Round 10: the probe runs on a BACKGROUND
    # thread (pure telemetry — nothing the loop depends on), so its
    # lower+compile overlaps the timed loop instead of sitting in the
    # ledger's compile phase; the result is joined after the loop.
    # (--hbm_budget runs already created it synchronously pre-warmup.)
    if flops_probe is None and probe_wanted:
        flops_probe = obs_efficiency.StepFlopsProbe(
            train_step, state, warm_batch, rng)
    # analytic memory table (obs.memory): params/opt/batch bytes from
    # the live shapes — pure host arithmetic, computed while the warmup
    # batch is still referenced; the post-run memory_report pairs it
    # with the probe's AOT byte account
    analytic_mem = obs_memory.analytic_memory_table(state, warm_batch)
    # drop the reference NOW: the probe only needed shapes, and holding
    # the last warmup batch through the timed run would pin one extra
    # device batch in HBM (max_inflight exists because batch HBM matters)
    warm_batch = None
    if cfg.metrics_dir:
        # the compile phase's memory high water (the warmup materialized
        # the step program's buffers for the first time)
        obs_writer.event("memory", **mem_ledger.sample("compile"))

    # --- timed loop (reference num_batches=100, display_every=10) ---
    # Fully asynchronous dispatch: the main thread never syncs, so the
    # device never waits on a host/tunnel round trip; progress is
    # observed by the shared _AsyncTimeline protocol.  The already-
    # fetched warmup loss is the t=0 marker, so the measured span covers
    # exactly the num_batches timed steps.
    units = _example_units(cfg, spec)
    timeline = _AsyncTimeline(cfg.num_batches, cfg.display_every,
                              global_batch)
    # windowed jax.profiler tracing (--profile_steps, or the legacy
    # first-sync-window default) — the structured replacement for the
    # reference's I_MPI_DEBUG=5 fabric tracing
    # (run-tf-sing-libfabric-intelmpi.sh:98)
    trace_window = _TraceWindow(cfg, print_fn, timeline.sync_every)
    timeline.start(metrics["loss"])
    phases.enter("step")
    hb_ewma = obs_fleet.StepEwma()
    warmup_steps = max(1, cfg.num_warmup_batches)

    # --- resilience runtime (round 8): fault-injection plan, preemption
    # handler, hung-step watchdog, non-finite guard tracking.  The guard
    # itself runs INSIDE the compiled step (train/step.py); here the
    # driver threads its per-step flag into device-side counters and pays
    # one scalar fetch per sync window to enforce policy.
    plan = inject_mod.parse_plan(cfg.inject_fault)
    policy = cfg.on_nonfinite
    tracker = (guards_mod.GuardTracker()
               if policy in ("skip", "rewind") else None)
    rewind_base_step = 0
    if policy == "rewind":
        # the absolute step counter at this RUN's start (nonzero on
        # --resume runs): rewind waste accounting must place checkpoint
        # stamps relative to this run's timed loop, not step 0 (the
        # post-warmup fetch is one tiny scalar, after the drain)
        rewind_base_step = (int(np.asarray(jax.device_get(state.step)))
                            - warmup_steps)
    world = jax.process_count()
    preempt_h = preempt_mod.PreemptionHandler(print_fn=print_fn).install()
    timeout_s = watchdog_mod.resolve_timeout(
        cfg.step_timeout_s, warmup_elapsed / warmup_steps)
    dog = None

    # async checkpoint writer (round 10): periodic saves overlap their
    # Orbax write with the step loop; only the device→host snapshot
    # blocks.  Synchronous whenever the save is collective or must
    # preserve the resilience exit-code contract: multi-host (Orbax
    # barriers + the sentinel wait are collective — a backgrounded
    # collective on some hosts is a deadlock), PP (restack/stacked
    # layouts), sharded states, io_error@ckpt injection (the retry
    # proof drives the sync path), and every emergency/preempt save.
    async_ckpt = None
    if (cfg.train_dir and cfg.async_checkpoint and world == 1
            and pp == 1 and not sharded_ckpt
            and not (plan is not None and plan.io_error)):
        from tpu_hc_bench.utils import checkpoint as ckpt_mod

        async_ckpt = ckpt_mod.AsyncCheckpointWriter(cfg.train_dir,
                                                    print_fn=print_fn)
        print_fn("checkpointing: async (snapshot blocks, write "
                 "overlapped, in-flight <= 1; emergency saves stay "
                 "synchronous)")

    def _drain_async_commits() -> None:
        """Move landed-save records from the writer thread's queue into
        the metrics stream — on the main thread, where MetricsWriter
        is safe to touch."""
        if async_ckpt is None:
            return
        while async_ckpt.commits:
            obs_writer.event("checkpoint_commit",
                             **async_ckpt.commits.popleft())

    def _flush_async_for_exit() -> None:
        """Land (or report) any in-flight overlapped save before a
        fatal-exit path closes the writers — a background write error
        or an unrecorded commit must not vanish under the budget/abort
        error that outranks it."""
        if async_ckpt is None:
            return
        try:
            async_ckpt.wait()
        except Exception as e:
            print_fn(f"WARNING: async checkpoint write failed during "
                     f"abort: {e}")
            obs_writer.event("async_ckpt_error", error=str(e))
        _drain_async_commits()

    def save_now(i: int, phase: str = "checkpoint") -> None:
        if async_ckpt is not None and phase == "checkpoint":
            # overlapped save: barrier on the previous write (usually
            # long landed — a save per sync window leaves a whole
            # window to finish), snapshot to host, hand off.  The
            # ledger's checkpoint_async phase records only this
            # blocking slice; the write's own seconds ride the
            # checkpoint_commit record it queues when it lands.
            if dog is not None:
                dog.pause()
            phases.enter("checkpoint_async", step=i)
            t_snap = time.monotonic()
            try:
                async_ckpt.submit(state, gc_keep=cfg.keep_checkpoints,
                                  topology=topo_rec)
                print_fn(f"checkpoint snapshot: step {i} "
                         f"({time.monotonic() - t_snap:.3f}s blocking; "
                         f"write overlapped)")
            finally:
                if cfg.metrics_dir:
                    # the snapshot's host copy of the full state is the
                    # phase's memory signature — attribute it
                    obs_writer.event("memory", **mem_ledger.sample(
                        "checkpoint_async", step=i))
                phases.enter("step", step=i)
                if dog is not None:
                    dog.resume()
            return
        def _do() -> None:
            if plan is not None:
                plan.maybe_io_error("ckpt")
            if pp_native_ckpt:
                from tpu_hc_bench.utils import checkpoint as ckpt_mod

                p, o = state
                path = ckpt_mod.save_pp(
                    p, o, pp_base_step + warmup_steps + i, cfg.train_dir,
                    topology=topo_rec)
                print_fn(f"checkpoint saved: {path} (PP-native)")
                return
            ctx = None
            if pp_save_ctx is not None:
                pp_model, pp_template, pp_base = pp_save_ctx
                # resume-aware stamp: continue the restored checkpoint's
                # step count so a resumed PP run never saves under a
                # lower step
                ctx = (pp_model, pp_template, pp_base + warmup_steps + i)
            _save_state(state, cfg, print_fn, pp_ctx=ctx,
                        sharded=sharded_ckpt, topology=topo_rec)

        # a multi-GB save to slow storage stalls the step loop
        # legitimately — the watchdog must not count it as a hang
        if dog is not None:
            dog.pause()
        phases.enter(phase, step=i)
        try:
            # multi-host saves are COLLECTIVE (Orbax barriers + the
            # commit-sentinel wait): a one-sided retry would leave the
            # retrier alone in a barrier, so retries are single-host only
            retry_io(_do, what="checkpoint save", print_fn=print_fn,
                     obs_writer=obs_writer,
                     attempts=1 if world > 1 else 3)
            if cfg.keep_checkpoints and cfg.train_dir:
                from tpu_hc_bench.utils import checkpoint as ckpt_mod

                # writer barrier: retention must never reap the .tmp an
                # in-flight overlapped save is still committing into
                ckpt_mod.gc_checkpoints(cfg.train_dir,
                                        cfg.keep_checkpoints,
                                        print_fn=print_fn,
                                        writer=async_ckpt)
        finally:
            if cfg.metrics_dir:
                obs_writer.event("memory", **mem_ledger.sample(
                    phase, step=i))
            phases.enter("step", step=i)
            if dog is not None:
                dog.resume()

    def _emergency(completed: int) -> None:
        """Preemption honored at a step boundary: one emergency
        checkpoint, metrics flush, distinct exit (launcher maps the
        raised PreemptedError to EXIT_PREEMPTED)."""
        print_fn(f"preemption: stopping after timed step {completed} "
                 f"(signal {preempt_h.signum})")
        phases.enter("emergency_save", step=completed)
        if async_ckpt is not None:
            # land (or surface the failure of) any in-flight overlapped
            # save before the emergency save claims the same directory
            async_ckpt.wait()
            _drain_async_commits()
        saved = bool(cfg.train_dir)
        if saved and tracker is not None:
            # settle the guard first: under rewind the state may carry
            # poisoned mid-window updates, and the emergency checkpoint
            # must never persist them for --resume=auto to restore
            try:
                _settle_guard(completed)
            except guards_mod.GuardBudgetError:
                saved = False   # budget died on poisoned state: keep it
                                # off disk, exit preempted without a save
        if saved:
            save_now(completed, phase="emergency_save")
            if not pp_native_ckpt:
                _fingerprint_line(
                    state.params if hasattr(state, "params") else state[0],
                    print_fn)
            obs_writer.event("emergency_ckpt", step=completed)
        if cfg.metrics_dir:
            # emergency forensics (obs.memory): what the devices held
            # when the run was killed — written BEFORE the streams
            # close, best-effort so it can never mask the preemption
            obs_writer.event("memory", **mem_ledger.sample(
                "emergency_save", step=completed))
            dpath = obs_memory.dump_forensics(
                cfg.metrics_dir, reason="emergency_save", step=completed,
                print_fn=print_fn)
            if dpath:
                obs_writer.event("memory_dump",
                                 path=os.path.basename(dpath),
                                 reason="emergency_save", step=completed)
            # time forensics beside the memory forensics: the last-K
            # spans per rank — what phase everyone was in at the kill
            tpath = timeline_mod.dump_timeline(
                cfg.metrics_dir, reason="emergency_save", step=completed)
            if tpath:
                obs_writer.event("timeline_dump",
                                 path=os.path.basename(tpath),
                                 reason="emergency_save", step=completed)
        obs_writer.event("preempt", step=completed,
                         signal=preempt_h.signum, checkpoint_saved=saved,
                         world=topo_rec.get("world"),
                         arm=topo_rec.get("variable_update"))
        phases.end(step=completed)
        obs_writer.close()
        fleet_writer.close()
        timeline_mod.detach()
        raise preempt_mod.PreemptedError(completed, saved, preempt_h.signum,
                                         topology=topo_rec)

    guard_seen_total = 0
    guard_last_poll_i = 0
    rewind_streak = 0
    # Non-blocking sync windows (round 10): the guard-counter fetch is
    # DOUBLE-BUFFERED.  At each sync window the driver snapshots the
    # device counters (refs only — no fetch) and fetches the PREVIOUS
    # window's snapshot: a full window of compute has drained behind
    # those scalars, so the device_get returns without stalling the
    # dispatch path, and the hot loop never synchronously round-trips
    # mid-run.  Policy therefore acts one window late; the settle paths
    # (_settle_guard: before saves, at preemption, at the final step)
    # flush the pipeline AND poll live, so no badness is ever persisted
    # to disk or survives the run unseen.
    guard_pending: list = []    # [(window_end_step, counter handles)]
    guard_wiped_until = -1      # a rewind's tracker.reset() wipes the
                                # counters for steps up to this stamp:
                                # that window must not pass as
                                # "observed clean" and break the
                                # consecutive-rewind budget

    def _apply_guard(j: int, streak: int, total: int, peak: int,
                     now_i: int) -> None:
        """Enforce --max_bad_steps / run the rewind restore on counters
        observed through step ``j`` (``now_i`` = the loop's current
        step — under the deferred fetch, later than ``j``)."""
        nonlocal guard_seen_total, guard_last_poll_i, rewind_streak
        nonlocal guard_wiped_until, state
        steps_since = j - guard_last_poll_i
        guard_last_poll_i = j
        new_bad = total - guard_seen_total
        if new_bad <= 0:
            # only a CLEAN window with actual steps in it breaks a rewind
            # streak — not a second poll at the same step (the settle-
            # before-save path), and not a window whose counters a
            # rewind's reset wiped (the post-restore replay span)
            if steps_since > 0 and j > guard_wiped_until:
                rewind_streak = 0
            return
        guard_seen_total = total
        if policy == "skip":
            print_fn(f"nonfinite: dropped {new_bad} update(s) in window "
                     f"ending step {j} (consecutive {streak}, "
                     f"total {total})")
            obs_writer.event("nonfinite_skip", step=j, new_bad=new_bad,
                             streak=streak, total=total)
            # dropped updates burned step time whose work was discarded:
            # the goodput ledger counts them against the run
            phases.note_skipped_updates(new_bad)
            # budget on the PEAK streak: a consecutive run that ended
            # inside the window (streak already reset by a good step)
            # still counts
            if peak >= cfg.max_bad_steps:
                _flush_async_for_exit()
                phases.end(step=j)
                obs_writer.close()
                fleet_writer.close()
                raise guards_mod.GuardBudgetError(
                    f"{peak} consecutive non-finite steps "
                    f"(--max_bad_steps={cfg.max_bad_steps})")
            return
        # rewind: restore the last complete checkpoint and re-enter the
        # loop with a skip-window over the offending data batches.
        # Budget matches the skip policy's: the run dies on the
        # max_bad_steps-th consecutive bad window.
        rewind_streak += 1
        if rewind_streak >= cfg.max_bad_steps:
            _flush_async_for_exit()
            phases.end(step=j)
            obs_writer.close()
            fleet_writer.close()
            raise guards_mod.GuardBudgetError(
                f"{rewind_streak} consecutive rewinds without a clean "
                f"window (--max_bad_steps={cfg.max_bad_steps})")
        from tpu_hc_bench.utils import checkpoint as ckpt_mod

        phases.enter("rewind_replay", step=now_i)
        if dog is not None:
            dog.pause()     # a long restore from slow storage is not a hang
        try:
            if async_ckpt is not None:
                # the newest overlapped save must land (or its failure
                # surface) before we pick the checkpoint to restore
                async_ckpt.wait()
                _drain_async_commits()
            restored = ckpt_mod.restore(state, cfg.train_dir,
                                        sharded=sharded_ckpt)
            state = restored if sharded_ckpt else place_fn(restored)
        finally:
            if dog is not None:
                dog.resume()
        restored_step = int(np.asarray(jax.device_get(restored.step)))
        skip_n = timeline.sync_every
        for _ in range(skip_n):
            next(batch_iter)
        tracker.reset()
        guard_pending.clear()   # snapshot refs predate the reset: a
                                # deferred fetch would re-report the
                                # badness this restore just cured
        guard_wiped_until = now_i
        guard_seen_total = 0
        # every timed step since the restored checkpoint ran for nothing
        # — its updates were just discarded; the ledger re-attributes
        # that span as wasted (resume-aware: restored_step counts prior
        # runs' steps and this run's warmup)
        lost_steps = obs_goodput.rewind_lost_steps(
            now_i, restored_step, rewind_base_step, warmup_steps)
        phases.note_lost_steps(lost_steps)
        phases.enter("step", step=now_i)
        print_fn(f"rewind: non-finite step(s) in window ending step {j}; "
                 f"restored checkpoint step {restored_step}, skipping "
                 f"{skip_n} batches")
        obs_writer.event("rewind", step=now_i, restored_step=restored_step,
                         skipped_batches=skip_n, streak=streak,
                         lost_steps=lost_steps)

    def _fetch_guard(handles) -> tuple[int, int, int]:
        streak, total, peak = jax.device_get(list(handles))
        return int(streak), int(total), int(peak)

    def _settle_guard(i: int) -> None:
        """Flush the deferred guard pipeline, then poll the live
        counters — the one deliberate host sync of the resilience path,
        paid only where state is about to be persisted (saves,
        preemption) or the run is ending."""
        while guard_pending:
            j, handles = guard_pending.pop(0)
            _apply_guard(j, *_fetch_guard(handles), now_i=i)
        _apply_guard(i, *tracker.poll(), now_i=i)

    try:
        if timeout_s is not None:
            dog = watchdog_mod.Watchdog(
                timeout_s, lambda: timeline.fetcher.last_arrival_t,
                print_fn=print_fn,
                last_record_fn=lambda: obs_writer.last_record,
                obs_writer=obs_writer,
                forensics_fn=(
                    (lambda: (obs_memory.dump_forensics(
                        cfg.metrics_dir, reason="watchdog",
                        print_fn=print_fn),
                        timeline_mod.dump_timeline(
                            cfg.metrics_dir, reason="watchdog")))
                    if cfg.metrics_dir else None)).start()
            print_fn(f"watchdog armed: step timeout {timeout_s:.1f}s")
        if policy == "rewind":
            from tpu_hc_bench.utils import checkpoint as ckpt_mod

            if ckpt_mod.latest_step(cfg.train_dir) is None:
                save_now(0)     # rewind baseline: the post-warmup state
        for i in range(1, cfg.num_batches + 1):
            # step boundary: honor preemption.  Single-host checks the
            # local flag every step; multi-host runs the cross-host
            # agreement at sync-window boundaries only — it is a
            # collective and must execute at the same step everywhere.
            if world == 1:
                if preempt_h.requested():
                    _emergency(i - 1)
            elif ((i - 1) % timeline.sync_every == 0
                    and preempt_h.agreed(world)):
                _emergency(i - 1)
            trace_window.maybe_start(i, timeline.fetcher)
            t_dw = time.monotonic()
            batch = next(batch_iter)
            t_dispatch = time.monotonic()
            # host time blocked on the input pipeline — carved out of
            # the "step" phase by the ledger (a cheap float add here;
            # the jsonl write happens once per sync window), and the
            # same interval recorded as an input_wait span
            phases.note_data_wait(t_dispatch - t_dw)
            timeline_mod.record_span("input_wait", t_dw, t_dispatch,
                                     step=i)
            if plan is not None:
                plan.fire_step_faults(i, print_fn, obs_writer)
                batch = plan.poison_batch(i, batch, print_fn, obs_writer)
            state, metrics = train_step(
                state, batch, jax.random.fold_in(rng, warmup_steps + i))
            # host-side dispatch cost only (the step itself is async;
            # device progress is the fetch thread's device_step spans)
            timeline_mod.record_span("step_dispatch", t_dispatch,
                                     time.monotonic(), step=i)
            timeline.record(i, metrics["loss"])
            if tracker is not None:
                tracker.update(metrics["nonfinite"])
                if i == cfg.num_batches:
                    # run end: flush the deferred window AND the live
                    # counters — nothing may survive the run unseen
                    _settle_guard(i)
                elif i % timeline.sync_every == 0:
                    # double-buffered: fetch window N-1's counters
                    # (complete long ago — no stall) while window N's
                    # steps execute; snapshot this window's refs
                    if guard_pending:
                        j, handles = guard_pending.pop(0)
                        _apply_guard(j, *_fetch_guard(handles), now_i=i)
                    guard_pending.append((i, tracker.handles()))
            if i % timeline.sync_every == 0 or i == cfg.num_batches:
                # sync-window bookkeeping: flush the accumulated
                # data-wait into the ledger stream, beat this host's
                # heartbeat file, and (multi-host) run the device-backed
                # progress allgather.  The whole block is gated on
                # cfg.metrics_dir: a bare benchmark run must not pay a
                # memory-stats poll or a host-blocking collective inside
                # the timed loop for telemetry nobody recorded.  The
                # gate must be this launch-uniform IMMUTABLE flag — not
                # fleet_writer.enabled, which a host whose heartbeat
                # write failed flips to False unilaterally, and a
                # collective only some hosts enter is a deadlock.  The
                # condition is a function of i only, so the allgather
                # executes at the same step everywhere.
                phases.flush(i)
                _drain_async_commits()
                if cfg.metrics_dir:
                    hb_step = timeline.fetcher.fetched_step
                    ewma_ms = hb_ewma.update(hb_step)
                    # HBM ledger (obs.memory): ONE device-memory poll
                    # per sync window, phase-attributed, written as one
                    # `memory` record; the running peak rides this
                    # host's heartbeat under the unified name
                    obs_writer.event("memory",
                                     **mem_ledger.sample("step", step=i))
                    # flight recorder: persist this window's spans and
                    # stamp the heartbeat with the rank's current phase
                    # — the `watch` per-rank "where is it" column
                    timeline_mod.flush()
                    # input-service backpressure rides the heartbeat:
                    # ring occupancy now + consumer-wait delta this
                    # window, so a starved host is visible fleet-wide
                    hb_input = ({"input": svc_client.window_stats()}
                                if svc_client is not None else {})
                    fleet_writer.heartbeat(
                        step=hb_step, step_ewma_ms=ewma_ms,
                        mem_peak_bytes=mem_ledger.peak_bytes or None,
                        phase=timeline_mod.current_phase(),
                        **hb_input)
                    if world > 1:
                        skew = obs_fleet.straggler_gather(hb_step, ewma_ms)
                        if skew is not None:
                            obs_writer.event("straggler", step=i, **skew)
            if (cfg.train_dir and cfg.save_model_steps
                    and i % cfg.save_model_steps == 0
                    and i < cfg.num_batches):
                # NOTE: saving fetches the full state — it syncs the
                # device and perturbs the throughput measurement around
                # this step
                if tracker is not None:
                    # settle the guard first: under rewind the state may
                    # carry un-detected poisoned updates mid-window, and
                    # persisting them would make the poisoned checkpoint
                    # the one rewind restores (the save syncs on the
                    # state anyway, so the flush is free)
                    _settle_guard(i)
                save_now(i)
            trace_window.poll(timeline.fetcher.fetched_step)
    except BaseException:
        if dog is not None:
            dog.stop()
        raise
    finally:
        preempt_h.uninstall()
    losses: list[float] = []
    nonfinite_display: list[int] = []

    def line(i: int, rate: float, v) -> None:
        loss = float(np.asarray(v))
        losses.append(loss)
        if not np.isfinite(loss):
            nonfinite_display.append(i)
        print_fn(f"{i}\t{units}/sec: {rate:.1f}\tloss: {loss:.3f}")
        obs_writer.event("window", step=i, rate=rate,
                         step_ms=1e3 * global_batch / rate, loss=loss)

    try:
        # the watchdog stays armed THROUGH the drain: up to max_inflight
        # steps are still executing when the loop exits, and a collective
        # that deadlocks in that tail would otherwise hang finish()
        # forever with no stack dump (arrivals keep advancing during a
        # healthy drain, so no false positive)
        total_time = timeline.finish(line)
    finally:
        if dog is not None:
            dog.stop()
    trace_window.stop()     # no-op if the in-loop poll already stopped it
    if policy == "abort" and nonfinite_display:
        # the default non-finite policy: fail the run loudly (the
        # display-step losses the timeline already fetches are the
        # zero-cost detector) instead of printing a NaN table and
        # exiting 0 the way the reference would
        obs_writer.event("nonfinite_abort", steps=nonfinite_display[:16])
        _flush_async_for_exit()
        phases.end(step=cfg.num_batches)
        obs_writer.close()
        fleet_writer.close()
        timeline_mod.detach()
        raise guards_mod.NonFiniteError(
            f"non-finite loss at display step(s) "
            f"{nonfinite_display[:16]} (--on_nonfinite=abort; use skip "
            f"or rewind to survive, or inspect the data/lr)")
    if cfg.train_dir:
        save_now(cfg.num_batches)       # final state (tf_cnn train_dir)
    if async_ckpt is not None:
        # exit barrier: the final overlapped write must land (and any
        # background write error must surface) before the run reports
        # success; the wait is accounted as checkpoint_async blocking
        phases.enter("checkpoint_async", step=cfg.num_batches)
        async_ckpt.wait()
        _drain_async_commits()
    phases.end(step=cfg.num_batches)
    ledger = phases.ledger()
    total_rate = cfg.num_batches * global_batch / total_time
    per_chip = total_rate / layout.total_workers
    mean_ms = 1e3 * total_time / cfg.num_batches
    p50_ms = timeline.p50_step_ms()
    p50_gran = timeline.p50_granularity

    # MFU (obs.efficiency): the measured cost_analysis() figure when the
    # AOT probe ran, the analytic table (fwd+bwd ~= 3x forward FLOPs;
    # forward-only 1x) otherwise — source labeled, both recorded, loud
    # when they disagree >10%.  The background probe has had the whole
    # timed loop to finish; the join here is normally instant.
    measured_flops = (flops_probe.result() if flops_probe is not None
                      else None)
    flops_mult = 1.0 if cfg.forward_only else 3.0
    peak = hw.peak_flops(dtype=cfg.compute_dtype)
    analytic_step_flops = (flops_mult * spec.flops_per_example
                           * global_batch / layout.total_workers)
    mfu_rep = obs_efficiency.mfu_report(
        measured_flops, analytic_step_flops, mean_ms / 1e3, peak)

    result = BenchmarkResult(
        model=cfg.model,
        total_workers=layout.total_workers,
        global_batch=global_batch,
        total_images_per_sec=total_rate,
        images_per_sec_per_chip=per_chip,
        mean_step_ms=mean_ms,
        p50_step_ms=p50_ms,
        p50_step_granularity=p50_gran,
        mfu=mfu_rep["mfu"],
        final_loss=losses[-1] if losses else float("nan"),
        fabric=fab.value,
        goodput=ledger.goodput if ledger is not None else float("nan"),
        goodput_phases=({k: round(v, 3)
                         for k, v in ledger.seconds.items() if v > 0.0}
                        if ledger is not None else None),
        data_wait_frac=(ledger.seconds.get("data_wait", 0.0)
                        / ledger.wall_s
                        if ledger is not None and ledger.wall_s > 0
                        else float("nan")),
        input_service=(svc_client is not None
                       if cfg.data_dir is not None and not spec.is_text
                       else None),
        mfu_source=mfu_rep["mfu_source"],
        resume=resume_rec,
    )
    tsum = trace_window.post_summary()
    trace_rec = None
    if tsum is not None:
        from tpu_hc_bench.obs import trace as obs_trace

        # per-collective-kind split so the ceiling attribution can name
        # the collective, not just "collective time"
        coll_ops: dict[str, float] = {}
        overlap_rec = None
        try:
            # ONE trace load + track split serves both consumers
            # (profile traces run to hundreds of MB of JSON): the
            # per-kind durations fold from the same leaf intervals the
            # --overlap_grad_comm exposure attribution walks
            intervals = obs_trace.leaf_intervals(
                obs_trace.load_events(cfg.trace_dir))
            ops: dict[str, float] = {}
            for name, s, e in intervals:
                ops[name] = ops.get(name, 0.0) + (e - s)
            coll_ops = obs_efficiency.collective_kind_times(ops)
            overlap_rec = obs_efficiency.collective_overlap(intervals)
        except Exception:
            pass
        trace_rec = {"buckets": tsum.totals, "steps": len(tsum.steps),
                     "collective_ops": coll_ops}
        if overlap_rec is not None:
            trace_rec["overlap"] = overlap_rec
            for ln in obs_efficiency.overlap_lines(overlap_rec):
                print_fn(ln.strip())
        obs_writer.event("trace_buckets", **trace_rec)
    if hasattr(ds, "stats"):    # host decode-pool counters (real images)
        obs_writer.event("data", **ds.stats())
    if input_svc is not None:
        # host-level backpressure account (ring occupancy percentiles,
        # producer stalls, consumer waits) — the `obs summarize` input
        # line and `obs diff` delta row read this record
        obs_writer.event("input_service", **input_svc.stats())
    if svc_client is not None:
        svc_client.close()
    if input_svc is not None:
        input_svc.stop()
    # final memory sample + the compile-time report (obs.memory): the
    # ledger's high water and its phase ride the summary; the AOT
    # memory_analysis() byte account is cross-checked against the
    # analytic params+opt+batch table (same 10% tripwire as MFU)
    obs_writer.event("memory",
                     **mem_ledger.sample("step", step=cfg.num_batches))
    mem_an = (flops_probe.memory_analysis()
              if flops_probe is not None else None)
    mem_rep = obs_memory.memory_report(mem_an, analytic_mem)
    obs_writer.event("memory_report", **mem_rep)
    result.peak_hbm_bytes = mem_ledger.peak_bytes or None
    result.hbm_bytes_limit = mem_ledger.bytes_limit
    result.mem_source = mem_ledger.source
    result.memory_analysis = mem_an
    # gradient-allreduce wire bytes (the dominant collective): what the
    # fabric-ceiling attribution divides by.  DP/SP/TP psum+GSPMD arms
    # only — PP's pipeline and the host fabric reduce differently.
    summary_fields = dict(result.json_line())
    summary_fields.update(mfu_rep)
    if (not cfg.forward_only and pp == 1
            and fab is not fabric_mod.Fabric.HOST
            and hasattr(state, "params")):
        accum_wire = (cfg.accum_dtype
                      if cfg.gradient_accumulation_steps > 1 else "f32")
        summary_fields["allreduce_bytes_per_step"] = \
            obs_efficiency.grad_allreduce_bytes(state.params, accum_wire)
    # round 24: the per-rank step-time sketch — bucket-wise mergeable
    # across ranks, so a fleet-wide step p50/p99 is one merge away
    step_sk = timeline.step_sketch()
    if step_sk is not None:
        obs_writer.event("latency_sketch", window=0,
                         fields={"step_ms": step_sk.to_record()})
    obs_writer.event("summary", **summary_fields)
    obs_writer.close()
    fleet_writer.close()
    timeline_mod.detach()       # flush the span tail, close spans.<k>.jsonl
    print_fn("-" * 40)
    print_fn(f"total {units}/sec: {total_rate:.2f}")
    # the p50 token names its own granularity: "/step" is a true per-step
    # median; "/N-step-window" admits the marker stream only resolved
    # N-step intervals (tunnel RTT > step time) — the honesty fix for
    # the old label that called window medians p50_step_ms
    p50_label = ("/step" if p50_gran == 1 else f"/{p50_gran}-step-window")
    print_fn(
        f"{units}/sec/chip: {per_chip:.2f}  step: {mean_ms:.2f}ms "
        f"(p50{p50_label} {p50_ms:.2f}ms)  MFU: {100 * result.mfu:.1f}% "
        f"({result.mfu_source})"
    )
    if mfu_rep.get("flops_disagree"):
        print_fn(obs_efficiency.mfu_lines(mfu_rep)[-1].strip())
    if ledger is not None:
        for ln in ledger.format_lines():
            print_fn(ln)
    for ln in obs_memory.memory_lines(mem_ledger.fold()):
        print_fn(ln.strip())
    if probe_wanted or mem_an:
        # bare runs never created the probe — printing the report's
        # "unavailable on this arm/backend" head there would blame a
        # backend that was simply never asked
        for ln in obs_memory.memory_report_lines(mem_rep):
            print_fn(ln.strip())
    if fabric_ceiling is not None:
        for ln in obs_efficiency.ceiling_utilization_lines(
                summary_fields, trace_rec, fabric_ceiling):
            print_fn(ln.strip())
    return result
