"""Train-step builders: data-parallel SGD with XLA-collective allreduce.

The reference's per-step hot loop (SURVEY.md §3.1) is: forward/backward on
MKL-DNN kernels -> Horovod DistributedOptimizer allreduce (C++ fusion
buffer, 128 MiB) -> OpenMPI/HCOLL -> UCX -> IB verbs.  The TPU-native step
compiles the whole thing into one XLA program: forward/backward on the MXU,
gradient ``psum`` over the mesh's data axis (optionally through the
Horovod-style fusion buckets of ``parallel.collectives``), optimizer update
fused in.  Four variable-update modes extend the reference's
``--variable_update`` choices (flags.py):

- ``psum`` (default; reference ``horovod``): ``jax.shard_map`` over the
  mesh — replicated params, sharded batch, explicit fused gradient psum.
  ``--overlap_grad_comm=on`` (default) packs the fusion buckets in
  backward-completion order so XLA's async collectives overlap the
  remaining backward compute; ``off`` barriers the full gradient tree
  first (the serialized control arm).
- ``replicated``: GSPMD — params/batch get shardings, XLA inserts the
  collectives itself (the idiomatic-JAX arm of the A/B).
- ``zero1``: ZeRO-1 optimizer-state sharding — gradients reduce-SCATTER
  over the data axis (same fusion buckets, half the allreduce's ring
  traffic), each device owns and updates 1/N of the optimizer state
  (stacked ``[N, k]`` leaves sharded over the data axis), then the
  updated parameter shards all-gather back to replicated params.  Same
  Horovod per-worker-BN semantics as ``psum``; per-device optimizer
  bytes drop ~1/N — the HBM lever for the big-param members.
- fabric ``host`` (reference ``sock``): per-device grads are stacked to
  host, averaged in numpy, update applied on host — the slow-fallback
  smoke path.

BatchNorm: per-worker batch statistics during the step (Horovod semantics),
then cross-worker ``pmean`` of the updated running stats so the replicated
state stays bitwise-identical on every device.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hc_bench.flags import BenchmarkConfig
from tpu_hc_bench.models import ModelSpec
from tpu_hc_bench.parallel.collectives import (
    all_gather_tree, allreduce_gradients, fused_psum_tree,
    reduce_scatter_tree, zero1_shard_len,
)
from tpu_hc_bench.parallel import fabric as fabric_mod
from tpu_hc_bench.resilience import guards
from tpu_hc_bench.topology import DATA_AXIS


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any            # {} for models without BN
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)


def make_optimizer(cfg: BenchmarkConfig) -> optax.GradientTransformation:
    """--optimizer dispatch (reference pins momentum, :74)."""
    lr = cfg.init_learning_rate
    if cfg.optimizer == "momentum":
        return optax.sgd(lr, momentum=cfg.momentum)
    if cfg.optimizer == "sgd":
        return optax.sgd(lr)
    if cfg.optimizer == "adam":
        return optax.adam(lr)
    if cfg.optimizer == "adamw":
        return optax.adamw(lr)
    if cfg.optimizer == "rmsprop":
        return optax.rmsprop(lr, decay=0.9, eps=1.0)  # tf_cnn rmsprop params
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


def make_train_state(
    model, cfg: BenchmarkConfig, example_batch: tuple, rng: jax.Array | None = None
) -> TrainState:
    """Initialize params on host-side abstract init, then TrainState."""
    rng = rng if rng is not None else jax.random.PRNGKey(cfg.seed)
    inputs = example_batch[0]
    # jit the whole init: one compiled program instead of hundreds of eager
    # ops (eager dispatch is pathological over remote/tunneled devices)
    init_fn = jax.jit(functools.partial(model.init, train=False))
    variables = init_fn(
        {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
        jnp.asarray(inputs[:1]),
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = make_optimizer(cfg)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )


def abstract_train_state(
    model, cfg: BenchmarkConfig, example_batch: tuple
) -> TrainState:
    """Host-side zero-filled TrainState — a checkpoint template.

    Same tree structure/dtypes as ``make_train_state`` but built from
    ``jax.eval_shape``, so it allocates NO device memory (host zeros are
    copy-on-write pages).  Used where a template must coexist with a
    sharded model that may not fit one device (the PP checkpoint
    interchange).
    """
    rng = jax.random.PRNGKey(cfg.seed)
    inputs = np.asarray(example_batch[0])
    shapes = jax.eval_shape(
        functools.partial(model.init, train=False),
        {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
        jax.ShapeDtypeStruct(inputs[:1].shape, inputs.dtype),
    )
    tx = make_optimizer(cfg)
    params_s = shapes["params"]
    opt_s = jax.eval_shape(tx.init, params_s)
    zeros = lambda tree: jax.tree.map(
        lambda s: np.zeros(s.shape, s.dtype), tree)
    return TrainState(
        step=np.zeros((), np.int32),
        params=zeros(params_s),
        batch_stats=zeros(shapes.get("batch_stats", {})),
        opt_state=zeros(opt_s),
        apply_fn=model.apply,
        tx=tx,
    )


# ---------------------------------------------------------------------
# ZeRO-1 state layout (--variable_update=zero1)
#
# Params stay replicated (the all-gather restores them every step); the
# OPTIMIZER state is built over per-device parameter shards and sharded
# over the data axis.  Layout: every param leaf of ``size`` elements owns
# a shard of ``k = ceil(size / N)`` elements per device; the optimizer
# state's array leaves are stacked ``[N, k]`` (row i = device i's shard)
# and placed with ``P(DATA_AXIS)`` on the leading dim, scalar leaves
# (e.g. adam's count) replicate.  The layout depends only on the param
# shapes and N — NOT on the fusion threshold — so checkpoints survive
# threshold changes; a zero1 checkpoint is NOT interchangeable with a
# psum/replicated one (different opt-state shapes; Orbax fails loudly on
# the structure mismatch).


def _stack_param_shards(p: jax.Array, num_shards: int) -> jax.Array:
    """``[N, k]`` stacked shards of a leaf (zero-padded to ``N * k``)."""
    k = zero1_shard_len(p.size, num_shards)
    flat = p.reshape(-1)
    pad = num_shards * k - p.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(num_shards, k)


def _local_param_shard(p: jax.Array, idx, num_shards: int) -> jax.Array:
    """Device ``idx``'s 1-D shard of a (replicated) param leaf — the
    slice the sharded optimizer updates."""
    k = zero1_shard_len(p.size, num_shards)
    flat = p.reshape(-1)
    pad = num_shards * k - p.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return jax.lax.dynamic_slice(flat, (idx * k,), (k,))


def make_zero1_state(model, cfg: BenchmarkConfig, example_batch: tuple,
                     num_shards: int,
                     rng: jax.Array | None = None) -> TrainState:
    """TrainState for the zero1 arm: replicated params, optimizer state
    built over stacked ``[N, k]`` param shards.

    ``tx.init`` runs ON the stacked tree, which equals per-shard init
    stacked for every registry optimizer (their inits are elementwise —
    zeros_like traces/moments plus scalar counts).
    """
    base = make_train_state(model, cfg, example_batch, rng)
    stacked = jax.tree.map(
        lambda p: _stack_param_shards(p, num_shards), base.params)
    return base.replace(opt_state=jax.jit(base.tx.init)(stacked))


def zero1_opt_template(params, tx, num_shards: int):
    """Host zero-filled optimizer-state template in the zero1 stacked
    layout for ``num_shards`` devices — the restore target when a
    checkpoint was saved under a DIFFERENT world size
    (``utils.checkpoint.restore_elastic``): the on-disk ``[N_saved, k]``
    leaves restore into this, then ``resplit_zero1_opt`` re-lays them
    out for the live world.  Pure ``eval_shape`` + ``np.zeros`` — no
    device memory."""
    stacked = jax.eval_shape(
        lambda p: jax.tree.map(
            lambda x: _stack_param_shards(x, num_shards), p), params)
    shapes = jax.eval_shape(tx.init, stacked)
    return jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), shapes)


def resplit_zero1_opt(opt_state, params, tx, n_old: int, n_new: int):
    """Re-layout a gathered zero1 optimizer state from ``[n_old, k]``
    stacked shards to ``[n_new, k']`` — the elastic-resume reshard.

    Stacked leaves are identified by comparing abstract ``tx.init``
    templates over the n_old-stacked vs n_new-stacked params: a leaf
    whose shapes AGREE between the two is stacking-invariant (scalar
    counts, schedule state — their shapes never depend on N; and when
    ``n_old == n_new`` every leaf trivially agrees and the identity is
    correct), because for a genuinely stacked leaf
    ``(n_old, ceil(s/n_old)) == (n_new, ceil(s/n_new))`` forces
    ``n_old == n_new``.  Comparing against the RAW-params template
    instead would misclassify any param whose own shape coincides with
    its stacked layout (e.g. a ``(n_old, k)`` kernel) and silently skip
    its resplit.  Stacked leaves are resplit on host via
    ``collectives.zero1_resplit_rows`` (strip old padding, re-pad for
    the new axis) — bitwise on the real elements in both directions.
    """
    from tpu_hc_bench.parallel.collectives import zero1_resplit_rows

    def stacked_opt_abs(n):
        stacked = jax.eval_shape(
            lambda p: jax.tree.map(
                lambda x: _stack_param_shards(x, n), p), params)
        return jax.eval_shape(tx.init, stacked)

    old_abs = stacked_opt_abs(n_old)
    new_abs = stacked_opt_abs(n_new)
    ref_abs = jax.eval_shape(tx.init, params)

    def conv(leaf, old_s, new_s, ref_s):
        if tuple(old_s.shape) == tuple(new_s.shape):
            return leaf        # stacking-invariant (or n_old == n_new)
        size = int(np.prod(ref_s.shape)) if ref_s.shape else 1
        return zero1_resplit_rows(np.asarray(jax.device_get(leaf)),
                                  size, n_new)

    return jax.tree.map(conv, opt_state, old_abs, new_abs, ref_abs)


def zero1_opt_specs(opt_state, num_shards: int):
    """PartitionSpec pytree for a zero1 optimizer state: stacked
    ``[N, ...]`` array leaves shard over the data axis, scalars (step
    counts, schedule state) replicate."""
    return jax.tree.map(
        lambda x: (P(DATA_AXIS)
                   if getattr(x, "ndim", 0) >= 2
                   and x.shape[0] == num_shards else P()),
        opt_state)


def place_zero1_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place a zero1 TrainState: everything replicated except the
    optimizer state's stacked leaves, which shard over the data axis."""
    num_shards = mesh.shape[DATA_AXIS]
    repl = NamedSharding(mesh, P())
    specs = zero1_opt_specs(state.opt_state, num_shards)
    opt_state = jax.tree.map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs, state.opt_state)
    return state.replace(
        step=jax.device_put(state.step, repl),
        params=jax.device_put(state.params, repl),
        batch_stats=jax.device_put(state.batch_stats, repl),
        opt_state=opt_state,
    )


def _zero1_state_specs(state: TrainState, opt_specs) -> TrainState:
    """A TrainState-shaped pytree of PartitionSpecs (shard_map
    in/out_specs for the zero1 step): replicated everywhere except the
    sharded optimizer leaves."""
    repl = lambda tree: jax.tree.map(lambda _: P(), tree)
    return TrainState(
        step=P(),
        params=repl(state.params),
        batch_stats=repl(state.batch_stats),
        opt_state=opt_specs,
        apply_fn=state.apply_fn,
        tx=state.tx,
    )


def prep_inputs(inputs):
    """uint8 wire format -> normalized float32, inside the compiled step.

    Companion of ``ImageNetDataset(wire_dtype="uint8")``: the host ships
    raw 8-bit crops (4x less host->device traffic), and the cast+normalize
    fuses into the step's first ops.  Float inputs pass through untouched;
    the dtype branch is static at trace time.
    """
    if inputs.dtype != jnp.uint8:
        return inputs
    from tpu_hc_bench.data.imagenet import IMAGENET_MEAN, IMAGENET_STD

    return (inputs.astype(jnp.float32) - IMAGENET_MEAN) / IMAGENET_STD


def _loss_and_updates(state: TrainState, params, batch, dropout_rng,
                      is_text: bool, fused_xent: bool = False,
                      ctc: bool = False):
    """Forward + loss; returns (loss, new_batch_stats)."""
    variables = {"params": params}
    has_stats = bool(state.batch_stats)
    if has_stats:
        variables["batch_stats"] = state.batch_stats
    rngs = {"dropout": dropout_rng}
    inputs = prep_inputs(batch[0])
    # "losses" collects sown auxiliary terms (MoE load-balance); models
    # without them just return an empty dict
    mutable = (["batch_stats"] if has_stats else []) + ["losses"]
    logits, updated = state.apply_fn(
        variables, inputs, train=True, rngs=rngs, mutable=mutable
    )
    new_stats = updated.get("batch_stats", {})
    aux_terms = jax.tree.leaves(updated.get("losses", {}))
    if ctc:
        # deepspeech2: CTC over logit frames (optax's forward-backward
        # scan); all frames are valid (fixed synthetic length), labels
        # carry per-example padding
        _, labels, label_paddings = batch
        logit_paddings = jnp.zeros(logits.shape[:2], jnp.float32)
        losses = optax.ctc_loss(logits, logit_paddings, labels,
                                label_paddings)
        loss = losses.mean()
    elif is_text:
        _, targets, weights = batch
        if fused_xent:
            # Pallas blocked CE: one pass over the [tokens, vocab] logits
            from tpu_hc_bench.ops import softmax_xent

            b, s, v = logits.shape
            losses = softmax_xent(
                logits.reshape(b * s, v), targets.reshape(b * s)
            ).reshape(b, s)
        else:
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            )
        loss = (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)
    else:
        _, labels = batch
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
    if aux_terms:
        from tpu_hc_bench.models.moe import AUX_LOSS_COEF

        loss = loss + AUX_LOSS_COEF * sum(jnp.sum(t) for t in aux_terms)
    return loss, new_stats


def build_train_step(
    mesh: Mesh,
    cfg: BenchmarkConfig,
    spec: ModelSpec,
    fab: fabric_mod.Fabric = fabric_mod.Fabric.ICI,
):
    """Return ``step(state, batch, rng) -> (state, metrics)`` for the fabric.

    The returned callable takes host or device arrays whose leading dim is
    the global batch; sharding/replication is handled inside.
    """
    is_text = spec.is_text
    ctc = getattr(spec, "ctc", False)
    fuse = cfg.variable_update == "psum"
    zero1 = cfg.variable_update == "zero1"
    # --overlap_grad_comm: backward-order buckets (XLA async collectives
    # overlap the remaining backward) vs a full-tree barrier (comm
    # strictly after the complete backward — the A/B control)
    overlap = getattr(cfg, "overlap_grad_comm", "on") == "on"
    guard = guards.guard_mode(cfg)      # --on_nonfinite: off|flag|skip
    from tpu_hc_bench.topology import DCN_AXIS, SEQ_AXIS as _SEQ

    # a bound seq axis (any size — size 1 is the degenerate-SP mode)
    # routes through the (data, seq) shard_map arm
    sp = (getattr(cfg, "sequence_parallel", 1) > 1
          or _SEQ in mesh.axis_names)
    tp = getattr(cfg, "model_parallel", 1) > 1

    dcn = DCN_AXIS in mesh.axis_names
    if dcn and (sp or tp or getattr(cfg, "expert_parallel", 1) > 1):
        raise ValueError(
            "multislice (dcn) currently composes with data parallelism "
            "only")
    if dcn and fab is fabric_mod.Fabric.HOST:
        raise ValueError("fabric=host has no multislice layout")

    accum = getattr(cfg, "gradient_accumulation_steps", 1)
    if accum > 1 and fab is fabric_mod.Fabric.HOST:
        # flags.resolve() rejects the other unsupported arms; the fabric
        # is only known here
        raise ValueError(
            "--gradient_accumulation_steps is not supported on the host "
            "(sock-analog) fabric step")
    if zero1:
        # flags.resolve rejects the TP/EP/PP/SP compositions at flag
        # time; these guards catch programmatic construction and the
        # layouts only known here (fabric, multislice)
        if fab is fabric_mod.Fabric.HOST:
            raise ValueError(
                "--variable_update=zero1 needs a device fabric (ici): "
                "the host (sock-analog) path has no sharded optimizer")
        if dcn:
            raise ValueError(
                "--variable_update=zero1 composes with single-slice data "
                "parallelism only (the multislice (dcn, data) hierarchical "
                "reduce has no reduce-scatter layout yet)")
        if sp or tp or getattr(cfg, "expert_parallel", 1) > 1:
            raise ValueError(
                "--variable_update=zero1 composes with plain data "
                "parallelism only")
    if fab is fabric_mod.Fabric.HOST:
        return _build_host_step(mesh, cfg, is_text, ctc=ctc)
    if not sp and (tp or getattr(cfg, "expert_parallel", 1) > 1):
        # TP/EP run on the GSPMD arm: params enter committed with
        # tp_param_spec shardings and jit follows them
        return _build_gspmd_step(mesh, cfg, is_text, follow_inputs=True,
                                 ctc=ctc)
    if not sp and cfg.variable_update == "replicated":
        return _build_gspmd_step(mesh, cfg, is_text, dcn=dcn, ctc=ctc)

    # --sequence_parallel: same explicit-psum step over a (data, seq) mesh
    # — batch sharded over both axes, gradients reduced (with the same
    # fusion buckets) over both; the model was built seq-axis-aware.
    # DP x SP x TP (3-D hybrid): data/seq stay *manual* shard_map axes
    # (the ring/Ulysses attention's explicit ppermutes need them) while the
    # model axis stays *auto* — params enter model-sharded per
    # tp_param_spec and GSPMD partitions the matmuls inside the manual
    # body, inserting the Megatron all-reduces itself.
    from tpu_hc_bench.topology import SEQ_AXIS

    # multislice: gradients reduce over (dcn, data) — XLA emits the
    # hierarchical allreduce with the cross-slice phase on DCN
    axes = (DATA_AXIS, SEQ_AXIS) if sp else (DATA_AXIS,)
    if dcn:
        axes = (DCN_AXIS,) + axes
    if sp and tp:
        # fusion buckets concatenate grad tensors, which would force
        # all-gathers of the model-sharded grads under the auto axis —
        # reduce per-tensor instead
        fuse = False

    acc_bf16 = getattr(cfg, "accum_dtype", "f32") == "bf16"

    def _accumulated_grads(state, batch, dropout_rng):
        """lax.scan over ``accum`` microbatches: per-microbatch forward +
        backward with microbatch-sized activations (the memory win remat
        buys by recompute, bought here by splitting), grads/loss/stats
        summed in explicit accumulator trees, ONE allreduce afterwards.

        Accumulator dtype (``--accum_dtype``): ``f32`` (default) sums in
        float32 regardless of the param/grad dtype and returns the mean
        cast back to the grad dtype — exact for the zoo's f32 params.
        ``bf16`` sums bfloat16-quantized microbatch grads and KEEPS the
        tree bf16 through the allreduce and into the optimizer (optax
        promotes against its f32 traces): the accumulator HBM footprint
        AND the gradient wire bytes halve — the lever for param-bound
        members whose +1x-params f32 tree OOMs (llama_1b, gpt2_moe).
        Precision depends on the accumulation count: each microbatch
        addition quantizes to bf16's ~2^-9 relative step, and the
        rounding errors random-walk, so the accumulated-gradient error
        grows ~sqrt(N)*2^-9 — ~3 significant digits at accum=2, but only
        ~1.5-2 digits (~1-3% relative) at the accum=16-64 configs
        sweep_zoo.py pins for the large members (pinned by the accum=32
        arm of tests/test_train.py's bf16-vs-f32 delta tests).  Loss and
        BN stats always accumulate in f32.

        Microbatch semantics (standard accumulation): each microbatch's
        loss is mean-normalized over its own examples/weights, then the
        N means are averaged — identical to the full batch for uniform
        weights, the usual approximation otherwise.

        BN running stats: each microbatch EMA-updates from the SAME
        starting stats and the results are averaged, i.e. the running
        statistics advance by ONE decay step per optimizer step (toward
        the mean of the microbatch statistics) — NOT the N chained
        decays a sequential-microbatch implementation (e.g. torch-style
        accumulation loops) would apply.  Train-mode forwards are
        unaffected (BN normalizes with per-microbatch batch stats
        either way); only the eval-time running-stat warm-up rate
        differs, and one decay per optimizer step is the consistent
        choice here.
        """
        local = jax.tree.leaves(batch)[0].shape[0]
        if local % accum:
            raise ValueError(
                f"per-device batch {local} is not divisible by "
                f"--gradient_accumulation_steps={accum}")
        micro = jax.tree.map(
            lambda x: x.reshape((accum, local // accum) + x.shape[1:]),
            batch)
        rngs = jax.random.split(dropout_rng, accum)

        def body(carry, xs):
            g_acc, l_acc, s_acc = carry
            mb, rng_i = xs

            def loss_fn(p):
                return _loss_and_updates(state, p, mb, rng_i, is_text,
                                         cfg.fused_xent, ctc)

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            # cast-then-add keeps the bf16 arm's sum in bf16 (an f32 add
            # followed by a downcast would materialize the f32 tree the
            # arm exists to avoid); the f32 arm's cast is a promote
            g_acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), g_acc, grads)
            s_acc = jax.tree.map(
                lambda a, x: a + x.astype(a.dtype), s_acc, stats)
            return (g_acc, l_acc + loss, s_acc), None

        f32_like = lambda x: jnp.zeros(
            x.shape, jnp.promote_types(x.dtype, jnp.float32))
        init = (
            jax.tree.map(
                (lambda x: jnp.zeros(x.shape, jnp.bfloat16))
                if acc_bf16 else f32_like,
                state.params),
            jnp.zeros((), jnp.float32),
            jax.tree.map(f32_like, state.batch_stats),
        )
        (g, l, s), _ = jax.lax.scan(body, init, (micro, rngs))
        if acc_bf16:
            # mean stays bf16 end-to-end (allreduce + optimizer see bf16)
            grads = jax.tree.map(
                lambda x: (x.astype(jnp.float32) / accum
                           ).astype(jnp.bfloat16), g)
        else:
            grads = jax.tree.map(
                lambda x, p: (x / accum).astype(p.dtype), g, state.params)
        stats = jax.tree.map(
            lambda x, o: (x / accum).astype(o.dtype), s, state.batch_stats)
        return l / accum, stats, grads

    # zero1's shard_map specs depend on the optimizer-state STRUCTURE,
    # known only when the first state arrives; the lazy step wrapper
    # below fills this before device_step first traces
    zero1_specs: dict = {}

    def device_step(state: TrainState, batch, dropout_rng):
        # per-device: local shard of the batch, replicated state
        for a in axes:
            dropout_rng = jax.random.fold_in(
                dropout_rng, jax.lax.axis_index(a)
            )

        if accum > 1:
            loss, new_stats, grads = _accumulated_grads(
                state, batch, dropout_rng)
        else:
            def loss_fn(p):
                return _loss_and_updates(state, p, batch, dropout_rng,
                                         is_text, cfg.fused_xent, ctc)

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
        if zero1:
            # ZeRO-1: reduce-SCATTER the gradient buckets (each device
            # receives only its 1/N shard of the mean grads), update the
            # local optimizer-state + param shards, all-gather the
            # updated param shards back to replicated params
            num_shards = jax.lax.axis_size(DATA_AXIS)
            idx = jax.lax.axis_index(DATA_AXIS)
            grad_shards = reduce_scatter_tree(
                grads, axis_name=DATA_AXIS,
                threshold_bytes=cfg.fusion_threshold_bytes,
                average=True, overlap=overlap)
        else:
            grads = allreduce_gradients(
                grads,
                axis_name=axes,
                threshold_bytes=cfg.fusion_threshold_bytes,
                fuse=fuse,
                overlap=overlap,
            )
        loss = jax.lax.pmean(loss, axes)
        if new_stats:
            # sync running stats so replicated state stays identical —
            # through the SAME fusion buckets as the gradients (round 5:
            # the world=2 HLO count showed resnet20's 44 collectives vs
            # bert's 2 were per-tensor BN-stat pmeans; bucketing them
            # turns 42 latency-bound crossings into one)
            if fuse or zero1:
                new_stats = fused_psum_tree(
                    new_stats, axis_name=axes,
                    threshold_bytes=cfg.fusion_threshold_bytes,
                    average=True)
            else:
                new_stats = jax.tree.map(
                    lambda s: jax.lax.pmean(s, axes), new_stats
                )
        if zero1:
            opt_specs = zero1_specs["opt"]
            param_shards = jax.tree.map(
                lambda p: _local_param_shard(p, idx, num_shards),
                state.params)
            # the local view of a [N, k] P(data)-sharded opt leaf is
            # [1, k]: drop the shard dim for the update, restore it for
            # the out_specs
            local_opt = jax.tree.map(
                lambda s, x: x.reshape(x.shape[1:])
                if s == P(DATA_AXIS) else x,
                opt_specs, state.opt_state)
            updates, new_local_opt = state.tx.update(
                grad_shards, local_opt, param_shards)
            new_shards = optax.apply_updates(param_shards, updates)
            new_params = all_gather_tree(
                new_shards, state.params, axis_name=DATA_AXIS,
                threshold_bytes=cfg.fusion_threshold_bytes,
                overlap=overlap)
            new_opt = jax.tree.map(
                lambda s, x: x[None] if s == P(DATA_AXIS) else x,
                opt_specs, new_local_opt)
        else:
            updates, new_opt = state.tx.update(grads, state.opt_state,
                                               state.params)
            new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
        )
        if guard != "off":
            # --on_nonfinite: in-step non-finite detection on loss AND the
            # (post-allreduce) grad global norm; "skip" drops the update
            # with a select INSIDE this compiled program — the only
            # donation-safe spelling, since the input state's buffers are
            # donated to this step (resilience/guards.py)
            if zero1:
                # each device sees only its grad shards; the flag must
                # agree across devices or the skip-select would fork the
                # replicated state — sum the squared norm over the axis
                # (= global_norm**2 of the full mean-gradient tree)
                gsq = sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grad_shards))
                gsq = jax.lax.psum(gsq, DATA_AXIS)
                ok = guards.finite_flag(loss) & jnp.isfinite(gsq)
            else:
                ok = guards.finite_flag(loss, grads)
            if guard == "skip":
                new_state = guards.select_state(ok, new_state, state)
            return new_state, {"loss": loss,
                               "nonfinite": guards.nonfinite_metric(ok)}
        return new_state, {"loss": loss}

    if cfg.forward_only:
        def fwd_only(state, batch, dropout_rng):
            for a in axes:
                dropout_rng = jax.random.fold_in(
                    dropout_rng, jax.lax.axis_index(a)
                )
            loss, _ = _loss_and_updates(
                state, state.params, batch, dropout_rng, is_text,
                cfg.fused_xent, ctc,
            )
            return state, {"loss": jax.lax.pmean(loss, axes)}
        device_step = fwd_only

    replicated = P()
    # dcn+data both split the leading batch dim (one tuple group); the SP
    # pair splits batch dim 0 (data) and seq dim 1 separately
    sharded = P((DCN_AXIS, DATA_AXIS)) if dcn else P(*axes)
    if zero1:
        # the in/out specs must name each sharded optimizer leaf, and the
        # optimizer-state STRUCTURE is only known from a live state — so
        # the shard_map is built lazily on the first call and cached (the
        # structure is fixed for the run; a second structure would be a
        # driver bug and jit would reject it anyway)
        cell: dict = {}

        def step(state, batch, rng):
            fn = cell.get("fn")
            if fn is None:
                num_shards = mesh.shape[DATA_AXIS]
                zero1_specs["opt"] = zero1_opt_specs(state.opt_state,
                                                     num_shards)
                state_specs = _zero1_state_specs(state, zero1_specs["opt"])
                shard_fn = jax.shard_map(
                    device_step,
                    mesh=mesh,
                    in_specs=(state_specs, sharded, replicated),
                    out_specs=(state_specs, replicated),
                    check_vma=False,
                )
                fn = jax.jit(shard_fn, donate_argnums=(0,))
                cell["fn"] = fn
                # obs.efficiency AOT-lowers this handle (see below)
                step._jitted = fn
            return fn(state, batch, rng)

        return step
    manual: dict = {}
    if sp and tp:
        # partial-manual shard_map: data/seq manual, model auto (GSPMD)
        manual = {"axis_names": frozenset(axes)}
    shard_fn = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(replicated, sharded, replicated),
        out_specs=(replicated, replicated),
        check_vma=False,
        **manual,
    )
    jitted = jax.jit(shard_fn, donate_argnums=(0,))

    def step(state, batch, rng):
        return jitted(state, batch, rng)

    # obs.efficiency AOT-lowers the same jitted callable (on abstract
    # avals — donation-safe) for compiled.cost_analysis() measured FLOPs
    step._jitted = jitted
    return step


def _build_gspmd_step(mesh: Mesh, cfg: BenchmarkConfig, is_text: bool,
                      follow_inputs: bool = False, dcn: bool = False,
                      ctc: bool = False):
    """``--variable_update=replicated``: the pure-GSPMD arm.

    No shard_map, no explicit collectives: the step is written over the
    *global* batch, ``in_shardings`` marks the batch as split over the data
    axis and the state as replicated, and XLA's SPMD partitioner inserts
    the gradient all-reduce itself.  This is the idiomatic-JAX counterpart
    to the explicit Horovod-style psum path, and the A/B between them is
    the fusion-tuning experiment the reference ran via
    HOROVOD_FUSION_THRESHOLD (run-tf-sing-ucx-openmpi.sh:105).

    Semantics note: BatchNorm statistics here are computed over the global
    batch (sync-BN) rather than per-worker — the one observable difference
    from the Horovod-semantics psum path, inherent to GSPMD.
    """
    guard = guards.guard_mode(cfg)      # --on_nonfinite: off|flag|skip

    def step_fn(state: TrainState, batch, dropout_rng):
        if cfg.forward_only:
            loss, _ = _loss_and_updates(
                state, state.params, batch, dropout_rng, is_text,
                cfg.fused_xent, ctc,
            )
            return state, {"loss": loss}

        def loss_fn(p):
            return _loss_and_updates(state, p, batch, dropout_rng, is_text,
                                      cfg.fused_xent, ctc)

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
        new_state = state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=new_stats,
            opt_state=new_opt,
        )
        if guard != "off":
            # same in-step guard as the psum arm (donation-safe select)
            ok = guards.finite_flag(loss, grads)
            if guard == "skip":
                new_state = guards.select_state(ok, new_state, state)
            return new_state, {"loss": loss,
                               "nonfinite": guards.nonfinite_metric(ok)}
        return new_state, {"loss": loss}

    if follow_inputs:
        # TP: inputs arrive committed (shard_state_tp / shard_batch); jit
        # follows those shardings and GSPMD inserts the TP collectives
        jitted = jax.jit(step_fn, donate_argnums=(0,))
    else:
        from tpu_hc_bench.topology import DCN_AXIS

        repl = NamedSharding(mesh, P())
        data = NamedSharding(
            mesh, P((DCN_AXIS, DATA_AXIS)) if dcn else P(DATA_AXIS))
        jitted = jax.jit(
            step_fn,
            in_shardings=(repl, data, repl),
            out_shardings=(repl, repl),
            donate_argnums=(0,),
        )

    def step(state, batch, rng):
        return jitted(state, batch, rng)

    # see build_train_step: the handle obs.efficiency AOT-lowers for
    # compiled.cost_analysis() measured FLOPs
    step._jitted = jitted
    return step


def _build_host_step(mesh: Mesh, cfg: BenchmarkConfig, is_text: bool,
                     ctc: bool = False):
    """The `sock` path: grads computed per device, reduced through the host.

    Deliberately slow (device->host->device every step) but exercises the
    identical forward/backward, so it both smoke-tests without collectives
    and provides the slow arm of the fabric A/B (README.md:70-73).
    """

    def local_grads(state: TrainState, batch, dropout_rng):
        dropout_rng = jax.random.fold_in(
            dropout_rng, jax.lax.axis_index(DATA_AXIS)
        )

        def loss_fn(p):
            return _loss_and_updates(state, p, batch, dropout_rng, is_text,
                                      cfg.fused_xent, ctc)

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        # add leading device axis so out_specs can concatenate
        expand = lambda t: jax.tree.map(lambda x: x[None], t)
        return expand(grads), loss[None], expand(new_stats)

    grads_fn = jax.jit(jax.shard_map(
        local_grads,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P()),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False,
    ))

    @jax.jit
    def apply_update(state: TrainState, grads, new_stats):
        updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
        return state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=new_stats,
            opt_state=new_opt,
        )

    def step(state, batch, rng):
        stacked_grads, losses, stacked_stats = grads_fn(state, batch, rng)
        # ONE host reduce for grads+stats+loss: at world > 1 the stacked
        # arrays span hosts, and host_allreduce is the only fetch that
        # handles non-addressable shards (a bare device_get would throw)
        grads, stats, loss = fabric_mod.host_allreduce(
            (stacked_grads, stacked_stats, losses))
        state = apply_update(state, grads, stats)
        return state, {"loss": jnp.asarray(loss)}

    return step


def weighted_text_metrics(logits, targets, weights):
    """Per-shard weighted-CE numerator/denominator + weighted top-1
    correct count — THE one home of the text-eval metric formulas (the
    DP, TP/EP-GSPMD, and PP eval arms must all report the same numbers,
    so they all call this)."""
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, targets)
    num = (losses * weights).sum()
    den = weights.sum()
    correct = jnp.sum(
        (jnp.argmax(logits, -1) == targets) * weights).astype(jnp.float32)
    return num, den, correct


def build_eval_step(mesh: Mesh, cfg: BenchmarkConfig, spec: ModelSpec,
                    follow_inputs: bool = False, sp: bool = False,
                    dcn: bool = False, tp: bool = False):
    """Eval step (tf_cnn_benchmarks --eval): forward pass, loss + top-1.

    Uses running BN statistics (``train=False``) and no dropout.  Returns
    ``(loss, correct_count)`` reduced over the mesh.

    ``follow_inputs=True`` is the TP/EP arm (same trick as
    ``_build_gspmd_step(follow_inputs=True)``): the step is written over
    the global batch with no shard_map, the model-sharded params enter
    committed (``shard_state_tp``) and jit follows them — GSPMD inserts
    the Megatron all-reduces in the forward, so a TP-trained state
    evaluates in its native sharding instead of being re-replicated.

    ``sp=True`` is the sequence-parallel arm: shard_map over
    ``(data, seq)`` with the batch's [B, S] dims split over both axes and
    metrics psummed over both — same numbers as the DP arm by the shared
    ``weighted_text_metrics`` formulas.

    ``dcn=True`` (round 4) is the multislice arm: the batch dim splits
    over BOTH (dcn, data) and metrics psum hierarchically over them —
    exactly the train step's multislice reduction, forward-only.

    ``sp=True, tp=True`` (round 4) is the DP x SP x TP hybrid arm: the
    same partial-manual shard_map as the hybrid train step — data/seq
    stay manual (metric psums), the model axis stays auto, so the
    committed model shardings of ``shard_state_tp`` flow through and
    GSPMD inserts the Megatron all-reduces inside the manual body.
    """
    is_text = spec.is_text
    from tpu_hc_bench.topology import DCN_AXIS, SEQ_AXIS

    if dcn and sp:
        raise ValueError("multislice eval composes with data parallelism "
                         "only (matching the train step)")
    axes = (DATA_AXIS, SEQ_AXIS) if sp else (DATA_AXIS,)
    if dcn:
        axes = (DCN_AXIS,) + axes

    def device_eval(state: TrainState, batch):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, prep_inputs(batch[0]),
                                train=False)
        if is_text:
            _, targets, weights = batch
            num, den, correct = weighted_text_metrics(
                logits, targets, weights)
            if not follow_inputs:
                # psum numerator/denominator separately: the GLOBAL
                # weighted mean (a mean of per-shard means would weight
                # shards equally regardless of their valid-token counts,
                # and the DP vs TP eval arms must report the same number)
                num = jax.lax.psum(num, axes)
                den = jax.lax.psum(den, axes)
            loss = num / jnp.maximum(den, 1.0)
        else:
            _, labels = batch
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            if not follow_inputs:
                loss = jax.lax.pmean(loss, axes)
            correct = jnp.sum(jnp.argmax(logits, -1) == labels)
        correct = correct.astype(jnp.float32)
        if follow_inputs:
            # global-batch program: loss/correct are already global
            return loss, correct
        return loss, jax.lax.psum(correct, axes)

    if follow_inputs:
        return jax.jit(device_eval)
    # multislice: the (dcn, data) pair splits the leading batch dim as one
    # tuple group; SP splits batch dim 0 (data) and seq dim 1 separately
    bspec = P((DCN_AXIS, DATA_AXIS)) if dcn else P(*axes)
    manual: dict = {}
    if sp and tp:
        manual = {"axis_names": frozenset(axes)}
    shard_fn = jax.shard_map(
        device_eval,
        mesh=mesh,
        in_specs=(P(), bspec),
        out_specs=(P(), P()),
        check_vma=False,
        **manual,
    )
    return jax.jit(shard_fn)


def tp_param_spec(path: str, ndim: int, mode: str = "tp") -> P:
    """Megatron-style tensor-parallel PartitionSpec for a transformer param.

    Column-parallel QKV/FFN-in (shard the output features over the model
    axis), row-parallel out-proj/FFN-down (shard the input features) — the
    classic layout where each block needs exactly one all-reduce per
    direction, which GSPMD inserts automatically.  Non-transformer params
    (and everything unmatched) replicate, so the rules are safe to apply to
    any model in the zoo.

    Matches all three naming schemes in the zoo: BERT's anonymous FFN
    denses (``Dense_0``/``Dense_1``), GPT's ``fc``/``proj``, and llama's
    ``wq``/``wk``/``wv``/``wo`` attention + ``gate``/``up``/``down``
    SwiGLU projections (Q/K/V and FFN-in column-parallel, out-proj and
    FFN-down row-parallel; GQA KV heads shard like Q heads, so the TP
    degree must divide ``num_kv_heads`` — ``jax.device_put`` rejects the
    uneven case loudly).

    ``mode="ep"`` (``--expert_parallel``) restricts the rules to the MoE
    expert tensors: whole experts shard over the model axis, the dense
    trunk (attention, norms, embeddings) stays replicated — pure expert
    parallelism rather than the TP+EP hybrid.
    """
    from tpu_hc_bench.topology import MODEL_AXIS as M

    rules = [
        ("qkv/kernel", P(None, None, M, None)),    # [C, 3, heads, d]
        ("qkv/bias", P(None, M, None)),            # [3, heads, d]
        ("out/kernel", P(M, None, None)),          # [heads, d, C]
        ("Dense_0/kernel", P(None, M)),            # FFN in  [C, ffn]
        ("Dense_0/bias", P(M)),
        ("Dense_1/kernel", P(M, None)),            # FFN out [ffn, C]
        ("fc/kernel", P(None, M)),
        ("fc/bias", P(M)),
        ("proj/kernel", P(M, None)),
        # llama family (models/llama.py): DenseGeneral QKV kernels are
        # [C, heads, head_dim] (kv: [C, kv_heads, head_dim]); wo is
        # [heads, head_dim, C]; SwiGLU gate/up [C, ffn], down [ffn, C]
        ("wq/kernel", P(None, M, None)),
        ("wk/kernel", P(None, M, None)),
        ("wv/kernel", P(None, M, None)),
        ("wo/kernel", P(M, None, None)),
        ("gate/kernel", P(None, M)),
        ("up/kernel", P(None, M)),
        ("down/kernel", P(M, None)),
        # expert parallelism: whole experts live on model-axis shards
        # (models/moe.py wi [E, H, F] / wo [E, F, H]); GSPMD turns the
        # [E]-sharded dispatch/combine einsums into expert all-to-alls
        ("moe/wi", P(M, None, None)),
        ("moe/wo", P(M, None, None)),
    ]
    if mode == "ep":
        rules = [r for r in rules if r[0].startswith("moe/")]
    for suffix, spec in rules:
        if path.endswith(suffix) and len(spec) == ndim:
            return spec
    return P()


def _param_specs(params, mode: str = "tp") -> dict:
    """Pytree of PartitionSpecs matching ``params`` via tp_param_spec."""
    return jax.tree_util.tree_map_with_path(
        lambda path, v: tp_param_spec(
            "/".join(getattr(k, "key", str(k)) for k in path), v.ndim, mode
        ),
        params,
    )


def shard_state_tp(state: TrainState, mesh: Mesh,
                   mode: str = "tp") -> TrainState:
    """Place the state with tensor/expert-parallel param shardings.

    Params (and the optimizer state, which mirrors the param tree — e.g.
    the momentum trace) are sharded per ``tp_param_spec``; everything else
    replicates.  The jitted GSPMD step then *follows* these committed
    shardings, so the same ``_build_gspmd_step`` serves DP, DP x TP, and
    DP x EP (``mode="ep"``).
    """
    specs = _param_specs(state.params, mode)
    if not any(
        s != P() for s in jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    ):
        if mode == "ep":
            raise ValueError(
                "expert_parallel > 1 but no param matched an expert rule: "
                "the model has no MoE layers (use an moe member, e.g. "
                "gpt2_moe), so EP would only halve the data-parallel degree"
            )
        raise ValueError(
            "model_parallel > 1 but no param matched a tensor-parallel "
            "rule: this model's param names have no TP layout (only the "
            "transformer families do), so TP would silently replicate "
            "every param and degrade to DP with a smaller global batch"
        )

    def put(spec_tree, tree):
        return jax.tree.map(
            lambda spec, x: jax.device_put(x, NamedSharding(mesh, spec)),
            spec_tree, tree,
        )

    params = put(specs, state.params)
    # optimizer state: shard any subtree whose structure mirrors params
    # (momentum/adam moments), replicate the rest (counts, empty states)
    def put_opt(node):
        if jax.tree.structure(node) == jax.tree.structure(state.params):
            return put(specs, node)
        return jax.device_put(node, NamedSharding(mesh, P()))

    opt_state = jax.tree.map(
        put_opt, state.opt_state,
        is_leaf=lambda n: jax.tree.structure(n)
        == jax.tree.structure(state.params),
    )
    rest = NamedSharding(mesh, P())
    return state.replace(
        step=jax.device_put(state.step, rest),
        params=params,
        batch_stats=jax.device_put(state.batch_stats, rest),
        opt_state=opt_state,
    )


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place the state replicated over the mesh (params live on-device)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(state, sharding)


def shard_batch(batch: tuple, mesh: Mesh, spec: P | None = None) -> tuple:
    """Place a global host batch sharded over the data axis (or ``spec`` —
    e.g. ``P(DATA_AXIS, SEQ_AXIS)`` for sequence-parallel token batches).
    On a multislice mesh the batch dim splits over BOTH (dcn, data)."""
    from tpu_hc_bench.topology import DCN_AXIS

    if spec is None:
        spec = (P((DCN_AXIS, DATA_AXIS))
                if DCN_AXIS in mesh.axis_names else P(DATA_AXIS))
    sharding = NamedSharding(mesh, spec)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def shard_batch_local(batch: tuple, mesh: Mesh,
                      spec: P | None = None) -> tuple:
    """Place a batch from per-process LOCAL rows (round 14).

    ``shard_batch`` takes the full global batch from every process and
    lets ``device_put`` keep the local slice — bitwise-safe but W-fold
    redundant on the host (each worker decodes/ships rows its devices
    never hold).  Here each process passes only its own rows and
    ``jax.make_array_from_process_local_data`` assembles the global
    array.  At world=1 the two are identical (the local rows ARE the
    global batch).  Callers gate on
    ``_compat.CAPABILITIES["process_local_arrays"]`` and fall back to
    ``shard_batch`` (the driver's ``--full_batch_identity`` arm).
    """
    from tpu_hc_bench.topology import DCN_AXIS

    if spec is None:
        spec = (P((DCN_AXIS, DATA_AXIS))
                if DCN_AXIS in mesh.axis_names else P(DATA_AXIS))
    sharding = NamedSharding(mesh, spec)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        batch)
