"""Budgeted autotuner over the model zoo — the closed performance loop.

Every headline number in BASELINE.md came from manual rounds of sweeping
the same handful of levers (per-chip batch, gradient accumulation,
``accum_dtype``, remat/scan, fusion threshold, gradient arm).  The
ingredients for automating that existed as separate modules — the search
driver (``scripts/sweep_zoo.py``), the objective (``obs`` goodput/MFU),
the pruner (``analysis`` lints), and cheap candidate evaluation (the
persistent compile cache) — but nothing connected them.  This package
is the connection:

- :mod:`tpu_hc_bench.tune.space` — the tunable levers per zoo member
  (batch as a power-of-two ladder, accum 1..64, accumulator dtype,
  remat/scan, fusion threshold, psum/zero1 arm) with per-member
  validity rules, plus the seeded best-known configs that used to live
  in ``sweep_zoo.py``.
- :mod:`tpu_hc_bench.tune.prune` — the static pruner: flag-time
  ``resolve()`` rejections, per-member ``analysis`` lint classes, and a
  small HBM model seeded from the best-known configs all skip
  candidates *before* paying for a run.
- :mod:`tpu_hc_bench.tune.runner` — the ONE subprocess runner (timeout,
  0/1/70/75 exit-code contract, JSON result parse) shared with
  ``scripts/sweep_zoo.py``.
- :mod:`tpu_hc_bench.tune.search` — budgeted successive halving with a
  resumable ``tune_state.json`` journal (tmp→rename commits, the
  ``utils/checkpoint.py`` idiom): measure every survivor briefly over
  one shared compile cache, keep the top half by goodput-adjusted
  throughput, re-measure longer.
- :mod:`tpu_hc_bench.tune.registry` — the tuned-config registry
  (``artifacts/tuned/<hardware_key>.json``; hardware key = chip
  generation + HBM + world size) that ``--config=auto`` consumes.

CLI::

    python -m tpu_hc_bench.tune search --model trivial --budget_s 600
    python -m tpu_hc_bench.tune show
    python -m tpu_hc_bench.tune promote --journal artifacts/tune/.../tune_state.json
"""

from tpu_hc_bench.tune.space import (  # noqa: F401
    Candidate,
    SEED_CONFIGS,
    member_space,
    seed_candidate,
)
from tpu_hc_bench.tune.registry import (  # noqa: F401
    hardware_key,
    lookup,
    promote,
)
