"""CLI: ``python -m tpu_hc_bench.tune`` — search | show | promote.

Examples::

    # budgeted search over trivial's lever space (axes mode), sharing
    # one compile cache, journaled + resumable under --out
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.tune search \\
        --model trivial --budget_s 600 --out artifacts/tune/trivial

    # re-enter the same --out after a preemption: completed
    # measurements are never re-run
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.tune search \\
        --model trivial --budget_s 600 --out artifacts/tune/trivial

    # promote the journal's best config into the registry row the
    # launcher's --config=auto resolves
    python -m tpu_hc_bench.tune promote \\
        --journal artifacts/tune/trivial/tune_state.json

    # what is tuned for this hardware?
    python -m tpu_hc_bench.tune show
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_search(args) -> int:
    from tpu_hc_bench.tune import prune as prune_mod
    from tpu_hc_bench.tune import registry as registry_mod
    from tpu_hc_bench.tune import search as search_mod

    hardware = args.hardware or registry_mod.hardware_key()
    models = []
    for m in args.model or []:
        models.extend(m.split(","))
    if not models:
        print("pass --model NAME (repeatable or comma-separated)",
              file=sys.stderr)
        return 2
    settings = search_mod.SearchSettings(
        budget_s=args.budget_s,
        rung0_batches=args.rung_batches,
        warmup=args.warmup,
        max_rungs=args.max_rungs,
        timeout_s=args.timeout_s,
        mode=args.mode,
        max_candidates=args.max_candidates,
    )
    lint_fn = (None if args.no_lints
               else prune_mod.baseline_lint_classes)
    rc = 0
    for model in models:
        out_dir = args.out or f"artifacts/tune/{model}-{hardware}"
        if args.out and len(models) > 1:
            # one journal per (model, out dir): a shared --out across
            # members would trip the journal's model guard
            out_dir = os.path.join(args.out, model)
        journal = search_mod.run_search(
            model, out_dir, hardware, settings=settings, lint_fn=lint_fn)
        if journal.get("best") is None:
            rc = 1
            continue
        if args.promote:
            path, row = registry_mod.promote(
                journal, registry_dir=args.registry)
            print(f"promoted: {model} -> {path}")
    return rc


def _render_journal(journal: dict) -> None:
    """One search journal's prune ledger: what the pruner skipped, why,
    and — for the hbm-oom class — which anchor provenance decided it
    (``measured`` journal rows vs the ``seeded`` best-known-config
    guess), plus the memory each landed measurement recorded."""
    print(f"search journal: {journal.get('model')} @ "
          f"{journal.get('hardware')} (status {journal.get('status')}, "
          f"{journal.get('spent_s', 0):.0f}s/"
          f"{journal.get('budget_s', 0):.0f}s budget)")
    skipped = journal.get("skipped") or []
    by_class: dict[str, int] = {}
    for s in skipped:
        by_class[s.get("class", "?")] = by_class.get(
            s.get("class", "?"), 0) + 1
    pruned = ", ".join(f"{k} x{v}" for k, v in sorted(by_class.items()))
    print(f"  pruned without a run: {len(skipped)}"
          + (f" ({pruned})" if pruned else ""))
    for s in skipped:
        if s.get("class") != "hbm-oom":
            continue
        print(f"    [hbm-oom/{s.get('hbm_source', '?')}] "
              f"{s.get('key')}: {s.get('reason')}")
    for key, meas in sorted((journal.get("measurements") or {}).items()):
        for rung, rec in sorted((meas or {}).items()):
            if not isinstance(rec, dict):
                continue
            # the key IS the lever assignment (kernel/block-size levers
            # like decode_attention=paged,decode_block_pages=2 render
            # here verbatim), so every measured row names its config
            parts = []
            if rec.get("score") is not None:
                parts.append(f"score {rec['score']:.4g}")
            peak = rec.get("peak_hbm_bytes")
            if peak:
                limit = rec.get("hbm_bytes_limit")
                parts.append(
                    f"peak {peak / 2**20:.1f} MiB"
                    + (f" of {limit / 2**30:.1f} GiB "
                       f"({peak / limit:.0%})" if limit else "")
                    + (f" [{rec['mem_source']}]"
                       if rec.get("mem_source") else ""))
            print(f"  measured: {key} rung {rung}"
                  + (": " + "; ".join(parts) if parts else ""))


def _cmd_show(args) -> int:
    import json as json_mod

    from tpu_hc_bench.tune import registry as registry_mod

    if getattr(args, "journal", None):
        with open(args.journal) as f:
            journal = json_mod.load(f)
        _render_journal(journal)
        return 0
    hardware = args.hardware or registry_mod.hardware_key()
    rows = registry_mod.load_rows(hardware, args.registry)
    path = registry_mod.registry_path(hardware, args.registry)
    if not rows:
        print(f"no tuned rows for hardware {hardware!r} ({path})")
        return 1
    print(f"tuned configs @ {hardware} ({path}):")
    for model in sorted(rows):
        row = rows[model]
        levers = ", ".join(f"{k}={v}"
                           for k, v in sorted(row["overrides"].items()))
        print(f"  {model:>16s}  score {row.get('score')}  "
              f"goodput {row.get('goodput')}  {levers}")
    return 0


def _cmd_promote(args) -> int:
    from tpu_hc_bench.tune import registry as registry_mod

    with open(args.journal) as f:
        journal = json.load(f)
    path, row = registry_mod.promote(
        journal, registry_dir=args.registry, hardware=args.hardware)
    print(f"promoted: {journal['model']} @ "
          f"{args.hardware or journal['hardware']} -> {path}")
    print(json.dumps(row, indent=2, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_hc_bench.tune",
        description="budgeted per-member config search over the zoo")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="run/resume a budgeted search")
    s.add_argument("--model", action="append",
                   help="zoo member (repeatable / comma-separated)")
    s.add_argument("--out", default=None,
                   help="journal + artifacts dir (default: "
                        "artifacts/tune/<model>-<hardware>); reuse the "
                        "same dir to resume")
    s.add_argument("--budget_s", type=float, default=3600.0,
                   help="wall-clock budget (journaled across resumes)")
    s.add_argument("--rung_batches", type=int, default=8,
                   help="timed steps at rung 0 (doubles per rung)")
    s.add_argument("--warmup", type=int, default=4)
    s.add_argument("--max_rungs", type=int, default=3)
    s.add_argument("--timeout_s", type=float, default=900.0,
                   help="per-measurement subprocess timeout")
    s.add_argument("--mode", choices=["axes", "grid"], default="axes")
    s.add_argument("--max_candidates", type=int, default=None,
                   help="cap the post-prune candidate count "
                        "(truncation is journaled)")
    s.add_argument("--hardware", default=None,
                   help="override the live hardware key")
    s.add_argument("--registry", default=None,
                   help="registry dir for --promote "
                        "(default artifacts/tuned)")
    s.add_argument("--promote", action="store_true",
                   help="promote the best config on completion")
    s.add_argument("--no-lints", action="store_true",
                   help="skip the per-member analysis-lint prune pass")
    s.set_defaults(fn=_cmd_search)

    s = sub.add_parser("show", help="render the registry rows, or a "
                                    "search journal's prune ledger")
    s.add_argument("--hardware", default=None)
    s.add_argument("--registry", default=None)
    s.add_argument("--journal", default=None,
                   help="path to a search's tune_state.json: print what "
                        "the pruner skipped and why (hbm-oom skips carry "
                        "their anchor provenance, measured|seeded) plus "
                        "each measurement's recorded HBM peak")
    s.set_defaults(fn=_cmd_show)

    s = sub.add_parser("promote",
                       help="journal best -> registry row")
    s.add_argument("--journal", required=True,
                   help="path to a search's tune_state.json")
    s.add_argument("--hardware", default=None)
    s.add_argument("--registry", default=None)
    s.set_defaults(fn=_cmd_promote)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
