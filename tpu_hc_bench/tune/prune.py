"""Static pruning: reject candidates before paying for a run.

Three free (or near-free) rejection classes, each recorded in the
search journal with its class tag so the ledger shows what the pruner
bought:

- ``flag-invalid`` — ``BenchmarkConfig.resolve()`` raises at flag time
  (the zero1 composition matrix, accum on the GSPMD arms, dtype lever
  without accumulation...).  The flag surface already encodes years of
  "died 50 warmup steps in" lessons; the pruner gets them for free.
- ``lint`` — per-member ``analysis`` findings (host-sync-in-jit,
  recompile hazards, sharding inconsistencies) not accepted by the
  checked-in baseline.  Evaluated once per member and cached — a member
  whose step program is statically broken skips its whole candidate
  class.
- ``hbm-oom`` — a small HBM occupancy model: a candidate whose
  *microbatch* (batch / accum — the activation-memory unit the chip
  actually holds) exceeds the model's anchor is a known-OOM skip.
  The anchor comes from one of two provenances, journaled per skip as
  ``hbm_source``:

  - ``measured`` (preferred): prior measurements — ``tune/runner``
    records every run's HBM high water + device limit (``obs.memory``)
    into the journal, and ``HbmModel.from_measurements`` extrapolates
    the largest microbatch the measured limit can hold (an OOM'd
    measurement caps the anchor below its own microbatch).
  - ``seeded`` (fallback): the best-known configs
    (``tune.space.SEED_CONFIGS``, the machine form of the BASELINE zoo
    table) — the seeded (batch, accum) pairing is assumed to sit near
    the HBM ceiling, with ``headroom`` slack, and a member whose seed
    NEEDED the bf16 accumulator rejects f32-accumulator candidates at
    or above the seeded batch (the f32 grad tree is the thing that
    OOMed).  Every memory fact here is a heuristic anchor — which is
    why measured rows win whenever they exist.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

from tpu_hc_bench.tune.space import Candidate, SEED_CONFIGS, seed_candidate

__all__ = ["Skip", "PruneResult", "HbmModel", "static_prune",
           "baseline_lint_classes", "hbm_model_for",
           "measured_rows_from_journal"]

FLAG_INVALID = "flag-invalid"
LINT = "lint"
HBM_OOM = "hbm-oom"


@dataclasses.dataclass(frozen=True)
class Skip:
    candidate: Candidate
    cls: str        # flag-invalid | lint | hbm-oom
    reason: str
    hbm_source: str | None = None   # hbm-oom only: measured | seeded

    def journal_record(self) -> dict:
        rec = {"key": self.candidate.key, "class": self.cls,
               "reason": self.reason}
        if self.hbm_source is not None:
            rec["hbm_source"] = self.hbm_source
        return rec


@dataclasses.dataclass
class PruneResult:
    survivors: list[Candidate]
    skipped: list[Skip]

    @property
    def skipped_classes(self) -> set[str]:
        return {s.cls for s in self.skipped}


@dataclasses.dataclass(frozen=True)
class HbmModel:
    """Known-OOM rejection seeded from a member's best-known config.

    ``max_microbatch`` is the anchor microbatch; ``needs_bf16_accum_at``
    is the seeded batch when the seed carries ``accum_dtype=bf16``
    (meaning the f32 accumulator tree is what OOMed there, BASELINE.md
    round 5).  ``source`` is the anchor's provenance — ``seeded`` (a
    best-known-config guess with ``headroom`` slack) or ``measured``
    (extrapolated from journaled HBM measurements, ``obs.memory``) —
    and is journaled with every hbm-oom skip.
    """

    max_microbatch: int
    headroom: float = 2.0
    needs_bf16_accum_at: int | None = None
    source: str = "seeded"

    @staticmethod
    def seeded(model: str, headroom: float = 2.0) -> "HbmModel | None":
        if model not in SEED_CONFIGS:
            return None
        seed = seed_candidate(model)
        d = dict(seed.overrides)
        batch = int(d["batch_size"])
        accum = int(d.get("gradient_accumulation_steps", 1))
        bf16_at = (batch if d.get("accum_dtype") == "bf16" else None)
        return HbmModel(max_microbatch=max(1, batch // accum),
                        headroom=headroom,
                        needs_bf16_accum_at=bf16_at)

    @staticmethod
    def from_measurements(rows: list[dict], headroom: float = 1.15,
                          needs_bf16_accum_at: int | None = None,
                          ) -> "HbmModel | None":
        """A measured anchor from journal measurement rows.

        Each row is a ``tune/runner`` record (``peak_hbm_bytes`` +
        ``hbm_bytes_limit`` from the run's ``obs.memory`` summary)
        joined with its candidate ``overrides``.  Two signals:

        - a SUCCESSFUL row extrapolates linearly: a microbatch of
          ``m`` peaking at ``p`` bytes of an ``L``-byte device fits up
          to ``m * L / (p * headroom)`` — the anchor takes the largest
          such estimate (and never less than the largest microbatch
          actually measured OK);
        - an OOM'd row is ground truth the other way: the anchor is
          capped strictly below that row's microbatch.

        ``needs_bf16_accum_at`` rides along from the seeded model (the
        caller grafts it): the f32-accumulator rejection is a state-
        memory fact independent of the microbatch anchor, and a
        measured anchor must not silently drop that skip class.

        Returns None when no row carries a measurement — the caller
        falls back to the seeded guess.
        """
        best_est = 0
        oom_cap: int | None = None
        for row in rows:
            micro = _row_microbatch(row)
            if micro is None:
                continue
            if _row_oomed(row):
                oom_cap = micro if oom_cap is None else min(oom_cap, micro)
                continue
            peak = row.get("peak_hbm_bytes") or 0
            limit = row.get("hbm_bytes_limit") or 0
            if peak <= 0:
                continue
            est = micro
            if limit > 0:
                est = max(micro, int(micro * limit / (peak * headroom)))
            best_est = max(best_est, est)
        if oom_cap is not None:
            best_est = (min(best_est, oom_cap - 1) if best_est
                        else oom_cap - 1)
        if best_est <= 0:
            return None
        # headroom=1.0: the measured anchor already IS the limit
        # estimate — stacking the seeded model's 2x guess band on top
        # would re-admit the OOM wall the measurement just mapped
        return HbmModel(max_microbatch=best_est, headroom=1.0,
                        needs_bf16_accum_at=needs_bf16_accum_at,
                        source="measured")

    def check(self, c: Candidate) -> str | None:
        """A rejection reason, or None when the candidate plausibly
        fits."""
        d = dict(c.overrides)
        batch = int(d.get("batch_size", 0)) or c.batch_size
        accum = int(d.get("gradient_accumulation_steps", 1))
        micro = max(1, batch // max(1, accum))
        limit = int(self.max_microbatch * self.headroom)
        if micro > limit:
            return (f"microbatch {micro} (batch {batch} / accum {accum}) "
                    f"exceeds the {self.source} HBM anchor "
                    f"{self.max_microbatch} x headroom "
                    f"{self.headroom:g} = {limit}")
        if (self.needs_bf16_accum_at is not None
                and accum > 1
                and d.get("accum_dtype", "f32") == "f32"
                and batch >= self.needs_bf16_accum_at):
            return (f"f32 accumulator tree at batch {batch}: the seeded "
                    f"config needed accum_dtype=bf16 at batch "
                    f"{self.needs_bf16_accum_at} (f32 tree OOMs)")
        return None


# the OOM spellings live in ONE place — obs.memory.is_oom_error (the
# forensics/warmup classifier); the pruner adds only its own journal
# class token.  Two drifting copies would mean a new backend's OOM
# spelling caps the forensics but not the measured anchor.
_PRUNE_OOM_TOKENS = ("hbm-oom",)


def _row_microbatch(row: dict) -> int | None:
    """The activation-memory unit of a measurement row: batch / accum
    from the candidate overrides the row was joined with."""
    d = row.get("overrides") or {}
    batch = int(d.get("batch_size", row.get("batch_size", 0)) or 0)
    if batch <= 0:
        return None
    accum = int(d.get("gradient_accumulation_steps", 1) or 1)
    return max(1, batch // max(1, accum))


def _row_oomed(row: dict) -> bool:
    from tpu_hc_bench.obs.memory import is_oom_error

    err = str(row.get("error") or "")
    return bool(err) and (is_oom_error(err)
                          or any(tok in err for tok in _PRUNE_OOM_TOKENS))


def measured_rows_from_journal(journal: dict,
                               model: str | None = None) -> list[dict]:
    """Join a search journal's measurement records with their candidate
    overrides — the row shape ``HbmModel.from_measurements`` consumes.
    Rows without a memory measurement AND without an OOM verdict carry
    no information and are dropped here."""
    rows: list[dict] = []
    if model is not None and journal.get("model") != model:
        return rows
    cands = journal.get("candidates") or {}
    for key, meas in (journal.get("measurements") or {}).items():
        overrides = (cands.get(key) or {}).get("overrides") or {}
        for rec in (meas or {}).values():
            if not isinstance(rec, dict):
                continue
            if not (rec.get("peak_hbm_bytes") or _row_oomed(rec)):
                continue
            row = dict(rec)
            row["overrides"] = dict(overrides)
            rows.append(row)
    return rows


def hbm_model_for(model: str,
                  measured_rows: list[dict] | None = None,
                  headroom: float = 2.0) -> "HbmModel | None":
    """The ONE place the anchor's provenance is decided: measured rows
    win whenever they yield a model; the seeded best-known-config guess
    is the fallback (None for members outside the seed table).  The
    seed's ``needs_bf16_accum_at`` fact is grafted onto a measured
    anchor — the f32-accumulator rejection is independent of the
    microbatch anchor and must survive the provenance switch."""
    seeded = HbmModel.seeded(model, headroom=headroom)
    if measured_rows:
        m = HbmModel.from_measurements(
            measured_rows,
            needs_bf16_accum_at=(seeded.needs_bf16_accum_at
                                 if seeded is not None else None))
        if m is not None:
            return m
    return seeded


@functools.lru_cache(maxsize=None)
def baseline_lint_classes(model: str) -> tuple[str, ...]:
    """Member-level lint regressions (findings the checked-in baseline
    does not accept) — evaluated once per member, cached.  This is the
    expensive pruner pass (it traces the model's jaxpr), so the search
    calls it through this cache and the stubbed tests inject their own
    ``lint_fn``."""
    from tpu_hc_bench.analysis import compare_to_baseline
    from tpu_hc_bench.analysis.lints import lint_model

    try:
        regressions = compare_to_baseline(lint_model(model))
    except Exception as e:        # a model that fails to trace is a skip
        return (f"lint pass failed to trace {model}: {e}",)
    return tuple(f.render() for f in regressions)


def static_prune(
    candidates: list[Candidate],
    hbm: HbmModel | None = None,
    lint_fn: Callable[[str], tuple[str, ...]] | None = None,
    measured_rows: list[dict] | None = None,
) -> PruneResult:
    """Partition candidates into survivors and classed skips.

    ``hbm=None`` resolves the HBM model through ``hbm_model_for``:
    measured journal rows when the caller has them, else the member's
    best-known-config seed (no-op for members outside the seed table).
    Every hbm-oom skip journals its anchor's provenance
    (``hbm_source=measured|seeded``).  ``lint_fn`` maps a member name
    to lint-regression reasons (default: none — the CLI passes
    ``baseline_lint_classes``; tests inject stubs).
    """
    survivors: list[Candidate] = []
    skipped: list[Skip] = []
    hbm_by_model: dict[str, HbmModel | None] = {}
    lint_by_model: dict[str, tuple[str, ...]] = {}
    for c in candidates:
        if c.model not in lint_by_model:
            lint_by_model[c.model] = lint_fn(c.model) if lint_fn else ()
        reasons = lint_by_model[c.model]
        if reasons:
            skipped.append(Skip(c, LINT, "; ".join(reasons)))
            continue
        try:
            c.to_config().resolve()
        except ValueError as e:
            skipped.append(Skip(c, FLAG_INVALID, str(e)))
            continue
        if c.model not in hbm_by_model:
            hbm_by_model[c.model] = (
                hbm if hbm is not None
                else hbm_model_for(c.model, measured_rows))
        model_hbm = hbm_by_model[c.model]
        reason = model_hbm.check(c) if model_hbm is not None else None
        if reason:
            skipped.append(Skip(c, HBM_OOM, reason,
                                hbm_source=model_hbm.source))
            continue
        survivors.append(c)
    return PruneResult(survivors=survivors, skipped=skipped)
