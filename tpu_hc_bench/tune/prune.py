"""Static pruning: reject candidates before paying for a run.

Three free (or near-free) rejection classes, each recorded in the
search journal with its class tag so the ledger shows what the pruner
bought:

- ``flag-invalid`` — ``BenchmarkConfig.resolve()`` raises at flag time
  (the zero1 composition matrix, accum on the GSPMD arms, dtype lever
  without accumulation...).  The flag surface already encodes years of
  "died 50 warmup steps in" lessons; the pruner gets them for free.
- ``lint`` — per-member ``analysis`` findings (host-sync-in-jit,
  recompile hazards, sharding inconsistencies) not accepted by the
  checked-in baseline.  Evaluated once per member and cached — a member
  whose step program is statically broken skips its whole candidate
  class.
- ``hbm-oom`` — a small HBM occupancy model seeded from the best-known
  configs (``tune.space.SEED_CONFIGS``, the machine form of the
  BASELINE zoo table): the seeded (batch, accum) pairing is the
  measured operating point near the HBM ceiling, so a candidate whose
  *microbatch* (batch / accum — the activation-memory unit the chip
  actually holds) exceeds that anchor by more than ``headroom`` is a
  known-OOM skip, and a member whose seed NEEDED the bf16 accumulator
  rejects f32-accumulator candidates at or above the seeded batch (the
  f32 grad tree is the thing that OOMed).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

from tpu_hc_bench.tune.space import Candidate, SEED_CONFIGS, seed_candidate

__all__ = ["Skip", "PruneResult", "HbmModel", "static_prune",
           "baseline_lint_classes"]

FLAG_INVALID = "flag-invalid"
LINT = "lint"
HBM_OOM = "hbm-oom"


@dataclasses.dataclass(frozen=True)
class Skip:
    candidate: Candidate
    cls: str        # flag-invalid | lint | hbm-oom
    reason: str

    def journal_record(self) -> dict:
        return {"key": self.candidate.key, "class": self.cls,
                "reason": self.reason}


@dataclasses.dataclass
class PruneResult:
    survivors: list[Candidate]
    skipped: list[Skip]

    @property
    def skipped_classes(self) -> set[str]:
        return {s.cls for s in self.skipped}


@dataclasses.dataclass(frozen=True)
class HbmModel:
    """Known-OOM rejection seeded from a member's best-known config.

    ``max_microbatch`` is the seeded batch/accum — the measured
    activation-memory operating point; ``needs_bf16_accum_at`` is the
    seeded batch when the seed carries ``accum_dtype=bf16`` (meaning
    the f32 accumulator tree is what OOMed there, BASELINE.md round 5).
    """

    max_microbatch: int
    headroom: float = 2.0
    needs_bf16_accum_at: int | None = None

    @staticmethod
    def seeded(model: str, headroom: float = 2.0) -> "HbmModel | None":
        if model not in SEED_CONFIGS:
            return None
        seed = seed_candidate(model)
        d = dict(seed.overrides)
        batch = int(d["batch_size"])
        accum = int(d.get("gradient_accumulation_steps", 1))
        bf16_at = (batch if d.get("accum_dtype") == "bf16" else None)
        return HbmModel(max_microbatch=max(1, batch // accum),
                        headroom=headroom,
                        needs_bf16_accum_at=bf16_at)

    def check(self, c: Candidate) -> str | None:
        """A rejection reason, or None when the candidate plausibly
        fits."""
        d = dict(c.overrides)
        batch = int(d.get("batch_size", 0)) or c.batch_size
        accum = int(d.get("gradient_accumulation_steps", 1))
        micro = max(1, batch // max(1, accum))
        limit = int(self.max_microbatch * self.headroom)
        if micro > limit:
            return (f"microbatch {micro} (batch {batch} / accum {accum}) "
                    f"exceeds the seeded HBM anchor {self.max_microbatch} "
                    f"x headroom {self.headroom:g} = {limit}")
        if (self.needs_bf16_accum_at is not None
                and accum > 1
                and d.get("accum_dtype", "f32") == "f32"
                and batch >= self.needs_bf16_accum_at):
            return (f"f32 accumulator tree at batch {batch}: the seeded "
                    f"config needed accum_dtype=bf16 at batch "
                    f"{self.needs_bf16_accum_at} (f32 tree OOMs)")
        return None


@functools.lru_cache(maxsize=None)
def baseline_lint_classes(model: str) -> tuple[str, ...]:
    """Member-level lint regressions (findings the checked-in baseline
    does not accept) — evaluated once per member, cached.  This is the
    expensive pruner pass (it traces the model's jaxpr), so the search
    calls it through this cache and the stubbed tests inject their own
    ``lint_fn``."""
    from tpu_hc_bench.analysis import compare_to_baseline
    from tpu_hc_bench.analysis.lints import lint_model

    try:
        regressions = compare_to_baseline(lint_model(model))
    except Exception as e:        # a model that fails to trace is a skip
        return (f"lint pass failed to trace {model}: {e}",)
    return tuple(f.render() for f in regressions)


def static_prune(
    candidates: list[Candidate],
    hbm: HbmModel | None = None,
    lint_fn: Callable[[str], tuple[str, ...]] | None = None,
) -> PruneResult:
    """Partition candidates into survivors and classed skips.

    ``hbm=None`` seeds the model from the member's best-known config
    (no-op for members outside the seed table).  ``lint_fn`` maps a
    member name to lint-regression reasons (default: none — the CLI
    passes ``baseline_lint_classes``; tests inject stubs).
    """
    survivors: list[Candidate] = []
    skipped: list[Skip] = []
    hbm_by_model: dict[str, HbmModel | None] = {}
    lint_by_model: dict[str, tuple[str, ...]] = {}
    for c in candidates:
        if c.model not in lint_by_model:
            lint_by_model[c.model] = lint_fn(c.model) if lint_fn else ()
        reasons = lint_by_model[c.model]
        if reasons:
            skipped.append(Skip(c, LINT, "; ".join(reasons)))
            continue
        try:
            c.to_config().resolve()
        except ValueError as e:
            skipped.append(Skip(c, FLAG_INVALID, str(e)))
            continue
        if c.model not in hbm_by_model:
            hbm_by_model[c.model] = (hbm if hbm is not None
                                     else HbmModel.seeded(c.model))
        model_hbm = hbm_by_model[c.model]
        reason = model_hbm.check(c) if model_hbm is not None else None
        if reason:
            skipped.append(Skip(c, HBM_OOM, reason))
            continue
        survivors.append(c)
    return PruneResult(survivors=survivors, skipped=skipped)
