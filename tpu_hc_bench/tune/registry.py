"""The tuned-config registry: ``artifacts/tuned/<hardware_key>.json``.

A tuned config is only meaningful on the hardware it was measured on,
so rows are keyed by a *hardware key* — chip generation + per-chip HBM
+ world size (``v5e-16gb-w4``, ``cpu-0gb-w1``).  One JSON file per
hardware key holds one row per zoo member: the winning lever overrides,
the score, and provenance (git sha, journal path, measured steps).

Consumers:

- ``--config=auto`` (``flags.BenchmarkConfig.resolve``): look up the
  row for (member, live hardware), apply its overrides to every lever
  the user left at the default, and record ``config_source=auto``; no
  row falls back LOUDLY to the BASELINE defaults
  (``config_source=baseline``) — never silently.
- ``python -m tpu_hc_bench.tune promote`` writes rows from a finished
  search journal; ``show`` renders them; ``scripts/sweep_zoo.py
  --from_registry`` re-validates them.

Environment overrides (tests, cross-machine workflows):
``TPU_HC_TUNE_REGISTRY`` points at a different registry dir;
``TPU_HC_TUNE_HW`` pins the hardware key without querying the backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path

__all__ = [
    "REGISTRY_ENV", "HW_ENV", "default_registry_dir", "registry_path",
    "hardware_key", "load_rows", "lookup", "promote", "resolve_auto",
]

REGISTRY_ENV = "TPU_HC_TUNE_REGISTRY"
HW_ENV = "TPU_HC_TUNE_HW"


def default_registry_dir() -> Path:
    env = os.environ.get(REGISTRY_ENV)
    if env:
        return Path(env)
    return Path(__file__).resolve().parents[2] / "artifacts" / "tuned"


def hardware_key(world: int | None = None) -> str:
    """``<chip-kind>-<hbm_gb>gb-w<world>`` from the live backend (or
    the ``TPU_HC_TUNE_HW`` pin).

    The three components are exactly what changes a best-known config:
    the chip generation (MXU shape/peak), the per-chip HBM (the batch
    and accumulator-dtype walls), and the world size (collective
    bytes/step, per-chip share of the global batch).
    """
    env = os.environ.get(HW_ENV)
    if env:
        return env
    import jax

    dev = jax.devices()[0]
    kind = dev.device_kind.lower().replace(" ", "_").replace("/", "_")
    hbm_gb = 0
    try:
        stats = dev.memory_stats() or {}
        hbm_gb = int(round(stats.get("bytes_limit", 0) / 2**30))
    except Exception:
        pass
    w = world if world is not None else jax.device_count()
    return f"{kind}-{hbm_gb}gb-w{w}"


def registry_path(hardware: str,
                  registry_dir: str | Path | None = None) -> Path:
    base = Path(registry_dir) if registry_dir else default_registry_dir()
    return base / f"{hardware}.json"


def load_rows(hardware: str,
              registry_dir: str | Path | None = None) -> dict:
    """The hardware key's member rows (``{}`` when none exist)."""
    path = registry_path(hardware, registry_dir)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return data.get("members", {})


def lookup(model: str, hardware: str,
           registry_dir: str | Path | None = None) -> dict | None:
    return load_rows(hardware, registry_dir).get(model)


def promote(journal: dict,
            registry_dir: str | Path | None = None,
            hardware: str | None = None) -> tuple[Path, dict]:
    """Write a finished journal's best config as the member's registry
    row (merging into the hardware file, tmp→rename committed).
    Returns (path, row)."""
    from tpu_hc_bench.tune.search import commit_json

    best = journal.get("best")
    if not best:
        raise ValueError(
            "journal has no successful measurement to promote "
            f"(status {journal.get('status')!r})")
    hardware = hardware or journal["hardware"]
    model = journal["model"]
    if journal.get("workload", "train") == "serve":
        # serving rows are lane-keyed: resolve_auto's serve lookup
        # reads `<model>@serve`, never the training key
        model = f"{model}@serve"
    rec = best.get("record") or {}
    row = {
        "overrides": dict(best["overrides"]),
        "base": dict(best.get("base") or {}),
        "score": best["score"],
        "images_per_sec_per_chip": rec.get("per_chip"),
        "goodput": rec.get("goodput"),
        "mfu_pct": rec.get("mfu_pct"),
        # the best RECORD's own measured length (a candidate promoted
        # off a shallower rung must not claim the final rung's steps)
        "measured_batches": rec.get(
            "measured_batches",
            journal["rungs"][-1]["batches"]
            if journal.get("rungs") else None),
        "search_status": journal.get("status"),
        "spent_s": journal.get("spent_s"),
    }
    path = registry_path(hardware, registry_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    if path.exists():
        data = json.loads(path.read_text())
    else:
        data = {"hardware": hardware, "members": {}}
    data["members"][model] = row
    commit_json(str(path), data)
    return path, row


def resolve_auto(cfg) -> str:
    """The ``--config=auto`` hook ``BenchmarkConfig.resolve`` calls.

    Mutates ``cfg`` in place: applies the registry row's lever
    overrides to every field the operator did not pin (an explicit
    user flag wins over the registry — the operator's
    ``--batch_size=64 --config=auto`` measures the tuned config AT that
    batch), stamps ``config_source`` (``auto`` | ``baseline``) and
    ``tuned_config``, and returns the translation note for the banner.

    "Pinned" means: named in ``cfg.explicit_flags`` when the config
    came through ``parse_flags`` (which records what the operator
    actually typed, so an explicit flag set to its default value still
    pins), else any field whose value differs from the dataclass
    default (the programmatic-construction fallback).
    """
    from tpu_hc_bench.flags import BenchmarkConfig

    hw = hardware_key()
    # the serving lane's rows are keyed `<model>@serve` — one member
    # can hold a tuned row per lane, and a training lookup can never
    # apply serving knobs (or vice versa)
    member = (f"{cfg.model}@serve"
              if getattr(cfg, "workload", "train") == "serve"
              else cfg.model)
    row = lookup(member, hw)
    if row is None:
        cfg.config_source = "baseline"
        have = sorted(load_rows(hw))
        return (f"auto->BASELINE defaults: no tuned row for "
                f"{member!r} at hardware {hw!r} "
                f"({registry_path(hw)}"
                + (f" has {', '.join(have)}" if have
                   else " does not exist")
                + ") — run `python -m tpu_hc_bench.tune search "
                  f"--model {cfg.model}`")
    defaults = {f.name: f.default
                for f in dataclasses.fields(BenchmarkConfig)}
    explicit = getattr(cfg, "explicit_flags", None)

    def pinned(k: str) -> bool:
        if explicit is not None:
            return k in explicit
        return getattr(cfg, k) != defaults.get(k)

    from tpu_hc_bench.tune.space import LEVERS, SERVE_LEVERS

    lane_levers = (SERVE_LEVERS
                   if getattr(cfg, "workload", "train") == "serve"
                   else LEVERS)
    applied, kept = [], []
    for k, v in {**row.get("base", {}), **row["overrides"]}.items():
        if not hasattr(cfg, k):
            # a stale row (flag renamed since the search) must not
            # crash every run; the tuned-config-staleness lint is the
            # loud gate for this
            kept.append(f"{k} (unknown flag)")
            continue
        if k in (LEVERS + SERVE_LEVERS) and k not in lane_levers:
            # a lane-crossed row (e.g. a hand-edited @serve row
            # spelling a training lever) — applying it would smuggle
            # the other lane's knob past resolve()'s validity matrix
            kept.append(f"{k} (not a {cfg.workload}-lane lever)")
            continue
        if not pinned(k):
            setattr(cfg, k, v)
            applied.append(f"{k}={v}")
        else:
            kept.append(f"{k}={getattr(cfg, k)} (explicit flag wins)")
    cfg.config_source = "auto"
    cfg.tuned_config = {"hardware": hw, "model": member, **row}
    note = (f"auto->tuned row {member}@{hw} "
            f"(score {row.get('score')}): "
            + (", ".join(applied) if applied else "no field changed"))
    if kept:
        note += "; kept: " + ", ".join(kept)
    return note
