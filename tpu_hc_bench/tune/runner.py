"""The ONE subprocess runner behind the sweep, the tuner, and the fleet.

``scripts/sweep_zoo.py``, the successive-halving search, and the fleet
supervisor (``tpu_hc_bench.fleet``) all need the same thing: launch
``python -m tpu_hc_bench 1 0 <batch> ici --model=<m> <flags...>`` in a
subprocess, enforce a timeout, classify the launcher's exit-code
contract (0 ok / 1 zero-throughput / 70 watchdog / 75 preempted —
``tpu_hc_bench.resilience.EXIT_CLASSES``, the one home), and parse one
result record.  Two diverging copies of that logic is how the old
regex miscounting bugs happened (ADVICE.md round 5), so it lives here
once.

Every launch puts the job in its OWN process group
(``start_new_session=True``) and every kill targets the *group*
(``kill_process_tree``): a training job hosts feeder threads, decode
pools, and — under the input service — real grandchild processes, and
a timeout/preempt that only killed the direct child would orphan them
onto the fleet's CPUs (the supervisor's zero-orphan soak invariant).

Result parsing prefers the machine-readable path: with ``metrics_dir``
set, the run's ``metrics.jsonl`` final ``summary`` record (the
BenchmarkResult fields as one JSON line, goodput included) is the
source of truth; the stdout ``images/sec/chip:`` line is the fallback
for runs without a metrics artifact.

The *score* the search ranks by is goodput-adjusted throughput:
``images_per_sec_per_chip x goodput`` — a config that wins on raw
step rate but spends its wall recompiling or blocked on input loses to
one that keeps the chip productive.  Runs without a ledger (NaN
goodput) fall back to the raw per-chip rate.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

# launcher exit-code contract (README "Fault tolerance" table) — the
# table lives with the codes in ``resilience``; this name is the
# long-standing import point for the sweep/tuner call sites
from tpu_hc_bench.resilience import EXIT_CLASSES, classify_exit

__all__ = ["run_one", "score", "parse_stdout_metrics", "EXIT_CLASSES",
           "classify_exit", "build_cmd", "launch_one",
           "kill_process_tree"]


def build_cmd(
    model: str,
    batch: int,
    flags: list[str] | None = None,
    *,
    warmup: int = 25,
    batches: int = 60,
    use_fp16: bool = True,
    workers: int = 0,
) -> list[str]:
    """The launcher command line for one member config (the positional
    ``NUM_HOSTS WORKERS BATCH FABRIC`` contract + tf_cnn-style flags).
    Shared by the blocking ``run_one`` and the fleet supervisor's
    non-blocking ``launch_one`` so there is exactly one spelling of the
    job-spec → argv translation."""
    cmd = [
        sys.executable, "-m", "tpu_hc_bench", "1", str(workers),
        str(batch), "ici",
        f"--model={model}",
        f"--num_warmup_batches={warmup}", f"--num_batches={batches}",
    ]
    if use_fp16:
        cmd.append("--use_fp16=True")
    cmd.extend(flags or [])
    return cmd


def launch_one(cmd: list[str], *, env: dict | None = None,
               cwd: str | None = None, stdout=None,
               stderr=subprocess.STDOUT) -> subprocess.Popen:
    """Start a job subprocess in its OWN session (and so its own
    process group): feeder pools and service grandchildren it spawns
    share the group, and ``kill_process_tree`` can reap the whole tree
    instead of orphaning them past the parent's death."""
    return subprocess.Popen(
        cmd, env=env, cwd=cwd, stdout=stdout, stderr=stderr,
        text=True, start_new_session=True)


def kill_process_tree(proc: subprocess.Popen,
                      sig: int = signal.SIGTERM,
                      grace_s: float = 5.0,
                      escalate: bool = True) -> None:
    """Signal the job's whole process group; with ``escalate`` (the
    timeout path), SIGKILL the group after ``grace_s`` if the leader is
    still alive.  ``escalate=False`` sends the one signal and returns —
    the fleet's graceful-preempt path, where the in-job handler needs
    its grace window to write the emergency checkpoint and the
    *supervisor* owns the escalation deadline.  Safe on an already-dead
    process, and falls back to the single process when the child shares
    our group (a caller that bypassed ``launch_one``)."""
    try:
        pgid = os.getpgid(proc.pid)
    except (ProcessLookupError, OSError):
        pgid = None
    own_group = False
    try:
        own_group = pgid is not None and pgid != os.getpgid(0)
    except OSError:
        pass

    def _send(s: int) -> None:
        try:
            if own_group:
                os.killpg(pgid, s)
            else:
                proc.send_signal(s)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def _group_alive() -> bool:
        # ANY surviving member counts — the leader exiting while a
        # SIGTERM-ignoring grandchild lives is exactly the orphan (and
        # held-open pipe) this escalation exists to reap
        if own_group:
            try:
                os.killpg(pgid, 0)
                return True
            except (ProcessLookupError, PermissionError, OSError):
                return False
        return proc.poll() is None

    _send(sig)
    if sig == signal.SIGKILL or not escalate:
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        proc.poll()             # reap the leader so its pgid can empty
        if not _group_alive():
            return
        time.sleep(0.05)
    if _group_alive():
        _send(signal.SIGKILL)


def parse_stdout_metrics(out: str) -> dict:
    """The legacy stdout parse: ``images/sec/chip: X  step: Yms
    (p50 ...)  MFU: W%`` (also the ``examples/sec/chip`` spelling)."""
    rec: dict = {}
    for line in out.splitlines():
        if line.startswith("images/sec/chip:") or "examples/sec/chip" in line:
            parts = line.replace("%", "").split()
            try:
                rec["per_chip"] = float(parts[1])
                rec["step_ms"] = float(parts[3].rstrip("ms"))
                rec["mfu_pct"] = float(parts[-2] if parts[-1].startswith("(")
                                       else parts[-1])
            except (IndexError, ValueError):
                pass
    return rec


def _read_summary(metrics_dir: str) -> dict | None:
    """The final ``summary`` record of the run's metrics.jsonl (None
    when the stream is missing or carries no summary)."""
    path = os.path.join(metrics_dir, "metrics.jsonl")
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "summary":
                    summary = rec
        return summary
    except OSError:
        return None


def run_one(
    model: str,
    batch: int,
    flags: list[str] | None = None,
    *,
    warmup: int = 25,
    batches: int = 60,
    timeout_s: float = 1800.0,
    metrics_dir: str | None = None,
    use_fp16: bool = True,
    env: dict | None = None,
    cwd: str | None = None,
) -> dict:
    """Run one member config in a subprocess; return one JSON-able
    record (the sweep's jsonl line shape, extended).

    Never raises on a failed run: timeouts, nonzero exits, and
    unparseable output all come back as a record with ``error`` set —
    the search treats those as score-0 candidates, the sweep writes
    them to the jsonl as-is.
    """
    flags = list(flags or [])
    if metrics_dir is not None:
        os.makedirs(metrics_dir, exist_ok=True)
        flags.append(f"--metrics_dir={metrics_dir}")
    cmd = build_cmd(model, batch, flags, warmup=warmup, batches=batches,
                    use_fp16=use_fp16)

    rec: dict = {"model": model, "batch_size": batch}
    if flags:
        rec["flags"] = flags
    t0 = time.time()
    proc = launch_one(cmd, env=env, cwd=cwd, stdout=subprocess.PIPE,
                      stderr=subprocess.PIPE)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # reap the WHOLE process group: a timed-out job's feeder pools /
        # service grandchildren must not outlive it (they would starve
        # every later measurement of host CPUs)
        kill_process_tree(proc)
        try:
            # drain pipes; bounded — an unkillable (D-state) survivor
            # holding the pipe must not wedge the whole search
            proc.communicate(timeout=10.0)
        except subprocess.TimeoutExpired:
            pass
        rec.update(wall_s=round(time.time() - t0, 1), error="timeout",
                   exit_class="timeout")
        return rec
    out = stdout + stderr
    rec["wall_s"] = round(time.time() - t0, 1)
    rec["returncode"] = proc.returncode
    if proc.returncode != 0:
        cls = classify_exit(proc.returncode)
        rec["exit_class"] = cls
        rec["error"] = (EXIT_CLASSES.get(proc.returncode)
                        or (out.strip().splitlines()[-1] if out.strip()
                            else "?"))
        return rec
    rec.update(parse_stdout_metrics(out))
    if metrics_dir is not None:
        summary = _read_summary(metrics_dir)
        if summary is not None:
            rec["per_chip"] = summary.get("images_per_sec_per_chip",
                                          rec.get("per_chip"))
            rec["step_ms"] = summary.get("mean_step_ms",
                                         rec.get("step_ms"))
            mfu = summary.get("mfu")
            if mfu is not None:
                rec["mfu_pct"] = round(100.0 * mfu, 2)
            gp = summary.get("goodput")
            # NaN goodput (no ledger) serializes as "NaN"/null — keep
            # only a real fraction
            if isinstance(gp, (int, float)) and gp == gp:
                rec["goodput"] = round(gp, 4)
            # measured device memory (obs.memory, round 15): the run's
            # HBM high water + the AOT byte account ride the journal so
            # the pruner's known-OOM model can anchor on MEASUREMENT
            # instead of the seeded guess (hbm_source=measured)
            if summary.get("peak_hbm_bytes"):
                rec["peak_hbm_bytes"] = int(summary["peak_hbm_bytes"])
                rec["mem_source"] = summary.get("mem_source")
            if summary.get("hbm_bytes_limit"):
                rec["hbm_bytes_limit"] = int(summary["hbm_bytes_limit"])
            ma = summary.get("memory_analysis")
            if isinstance(ma, dict):
                rec["memory_analysis"] = {
                    k: ma[k] for k in ("argument_bytes", "temp_bytes",
                                       "output_bytes", "total_bytes")
                    if k in ma}
    if "per_chip" not in rec:
        rec["error"] = "no-throughput-line"
    return rec


def score(rec: dict) -> float:
    """Goodput-adjusted per-chip throughput (the search objective).
    Failed runs score 0."""
    if rec.get("error"):
        return 0.0
    per_chip = rec.get("per_chip") or 0.0
    gp = rec.get("goodput")
    if isinstance(gp, (int, float)) and gp == gp and gp > 0:
        return per_chip * gp
    return per_chip
