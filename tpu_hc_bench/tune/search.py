"""Budgeted successive halving with a resumable journal.

The search protocol::

    enumerate (space) -> static prune -> rung 0: measure every survivor
    for a few steps -> keep the top half by goodput-adjusted throughput
    -> rung 1: re-measure 2x longer -> ... until one survivor, the rung
    cap, or the wall-clock budget.

All measurements share one ``--compile_cache`` dir (the PR-5 persistent
cache), so the marginal candidate costs its steps, not its compile —
the thing that makes a budgeted search affordable at all.

State lives in ``<out_dir>/tune_state.json`` and is committed after
*every* measurement with the tmp→``os.replace`` idiom from
``utils/checkpoint.py`` — a preempted search relaunched with the same
``out_dir`` resumes exactly where it died: pruner skips are replayed
from the journal (free), completed (candidate, rung) measurements are
never re-run, and the budget accounts the spent seconds across
sessions.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable

from tpu_hc_bench.tune import prune as prune_mod
from tpu_hc_bench.tune import runner as runner_mod
from tpu_hc_bench.tune.space import Candidate, member_space

__all__ = ["SearchSettings", "run_search", "load_journal",
           "JOURNAL_NAME", "commit_json"]

JOURNAL_NAME = "tune_state.json"
JOURNAL_VERSION = 1


def commit_json(path: str, payload: dict) -> None:
    """tmp → fsync → rename: a crash mid-write leaves the previous
    committed journal, never a truncated one (the checkpoint-layer
    commit idiom)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_journal(out_dir: str) -> dict | None:
    path = os.path.join(out_dir, JOURNAL_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


@dataclasses.dataclass
class SearchSettings:
    budget_s: float = 3600.0      # wall-clock budget (spent seconds are
                                  # journaled, so it spans resumes)
    rung0_batches: int = 8        # timed steps at rung 0
    warmup: int = 4               # warmup steps per measurement
    growth: int = 2               # rung r measures rung0 * growth**r
    keep_frac: float = 0.5        # survivors kept per rung
    max_rungs: int = 3
    timeout_s: float = 900.0      # per-measurement subprocess timeout
    mode: str = "axes"            # space enumeration (axes | grid)
    max_candidates: int | None = None   # cap AFTER pruning (journaled)
    use_fp16: bool = True


def _default_runner(model: str, out_dir: str,
                    settings: SearchSettings) -> Callable:
    """The real subprocess runner: one shared compile cache, one
    metrics dir per (candidate, rung) so goodput feeds the score."""
    from tpu_hc_bench._compat import CAPABILITIES

    cache_dir = os.path.join(out_dir, "compile_cache")

    def run(c: Candidate, rung: int, batches: int) -> dict:
        flags = c.to_flags()
        if CAPABILITIES["persistent_compilation_cache"]:
            flags.append(f"--compile_cache={cache_dir}")
        mdir = os.path.join(out_dir, "runs",
                            f"{c.key.replace('/', '_')}-r{rung}")
        return runner_mod.run_one(
            model, c.batch_size, flags,
            warmup=settings.warmup, batches=batches,
            timeout_s=settings.timeout_s, metrics_dir=mdir,
            use_fp16=settings.use_fp16)

    return run


def run_search(
    model: str,
    out_dir: str,
    hardware: str,
    settings: SearchSettings | None = None,
    runner: Callable[[Candidate, int, int], dict] | None = None,
    space: list[Candidate] | None = None,
    lint_fn: Callable[[str], tuple[str, ...]] | None = None,
    print_fn: Callable[[str], None] = print,
) -> dict:
    """Run (or resume) one member's budgeted search; return the final
    journal dict.

    ``runner(candidate, rung, batches) -> record`` defaults to the real
    subprocess runner; tests inject a stub with a synthetic throughput
    surface.  ``space`` defaults to ``member_space(model,
    settings.mode)``.
    """
    settings = settings or SearchSettings()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, JOURNAL_NAME)

    candidates = space if space is not None else member_space(
        model, mode=settings.mode)
    by_key = {c.key: c for c in candidates}

    journal = load_journal(out_dir)
    if journal is not None:
        if journal.get("model") != model:
            raise ValueError(
                f"journal at {path} is for model "
                f"{journal.get('model')!r}, not {model!r} — pick a "
                f"fresh --out dir")
        if journal.get("hardware") != hardware:
            raise ValueError(
                f"journal at {path} was searched on "
                f"{journal.get('hardware')!r}, not {hardware!r} — a "
                f"tuned config is per-hardware; pick a fresh --out dir")
        if journal.get("status") in ("complete", "all-failed"):
            # a FINISHED search is immutable: re-entering the rung loop
            # would burn budget on a fresh measurement past the
            # halving's stopping point (and relabel all-failed)
            print_fn(f"search at {path} already "
                     f"{journal['status']} (best: "
                     f"{(journal.get('best') or {}).get('key')}) — "
                     f"pick a fresh --out to search again")
            return journal
        print_fn(f"resuming search from {path}: "
                 f"{sum(len(v) for v in journal['measurements'].values())}"
                 f" measurement(s) already journaled, "
                 f"{journal.get('spent_s', 0.0):.0f}s spent")
        # the relaunch's budget is authoritative (a budget-exhausted
        # search resumed with a bigger budget keeps going)
        journal["budget_s"] = settings.budget_s
        journal["status"] = "running"
    else:
        result = prune_mod.static_prune(candidates, lint_fn=lint_fn)
        survivors = [c.key for c in result.survivors]
        truncated = 0
        if (settings.max_candidates is not None
                and len(survivors) > settings.max_candidates):
            # seed-first enumeration order: truncation keeps the seed
            # neighborhood; the journal says what was dropped — a
            # silent cap would read as "searched everything"
            truncated = len(survivors) - settings.max_candidates
            survivors = survivors[:settings.max_candidates]
        journal = {
            "version": JOURNAL_VERSION,
            "model": model,
            # the lane travels with the journal: promote() keys a serve
            # search's registry row `<model>@serve`, the key the
            # serving lane's --config=auto lookup reads — a bare-keyed
            # row would de-tune serving silently AND clobber the
            # member's training row
            "workload": (candidates[0].workload if candidates
                         else "train"),
            "hardware": hardware,
            "mode": settings.mode,
            "space_size": len(candidates),
            "skipped": [s.journal_record() for s in result.skipped],
            "truncated": truncated,
            "candidates": {c.key: {"overrides": dict(c.overrides),
                                   "base": dict(c.base)}
                           for c in candidates},
            "rungs": [],
            "measurements": {},
            "budget_s": settings.budget_s,
            "spent_s": 0.0,
            "survivors": survivors,
            "status": "running",
            "best": None,
        }
        commit_json(path, journal)
        by_class: dict[str, int] = {}
        for s in result.skipped:
            by_class[s.cls] = by_class.get(s.cls, 0) + 1
        pruned = ", ".join(f"{k} x{v}" for k, v in sorted(by_class.items()))
        print_fn(f"{model}: {len(candidates)} candidate(s), "
                 f"{len(result.skipped)} pruned without a run"
                 + (f" ({pruned})" if pruned else "")
                 + (f", {truncated} truncated by --max_candidates"
                    if truncated else "")
                 + f"; measuring {len(survivors)}")

    if runner is None:
        runner = _default_runner(model, out_dir, settings)

    def out_of_budget() -> bool:
        return journal["spent_s"] >= settings.budget_s

    survivors = list(journal["survivors"])
    rung = len(journal["rungs"])
    # a resumed search re-enters mid-rung: the rung loop below naturally
    # skips measurements already journaled
    while survivors and rung < settings.max_rungs:
        batches = settings.rung0_batches * settings.growth ** rung
        measured: list[tuple[str, dict]] = []
        exhausted = False
        for key in survivors:
            meas = journal["measurements"].setdefault(key, {})
            rec = meas.get(str(rung))
            if rec is None:
                if out_of_budget():
                    exhausted = True
                    break
                c = by_key.get(key) or _candidate_from_journal(
                    model, journal, key)
                # measured HBM re-check (obs.memory, round 15): every
                # landed measurement journals its peak bytes / OOM
                # verdict, so the known-OOM model re-anchors on
                # MEASUREMENT mid-search — a candidate the seeded guess
                # admitted is skipped for free once a measured row says
                # it cannot fit.  Candidates with their own successful
                # prior measurement are exempt: their row IS evidence
                # they fit, and a contradictory anchor (mixed dtypes,
                # a noisy limit estimate) must not retro-evict them.
                if not any(isinstance(r, dict) and not r.get("error")
                           for r in meas.values()):
                    mm = prune_mod.HbmModel.from_measurements(
                        prune_mod.measured_rows_from_journal(journal))
                    reason = mm.check(c) if mm is not None else None
                    if reason is not None:
                        # journal once: a resumed session re-enters the
                        # rung and re-derives the same verdict — the
                        # ledger must not grow a duplicate row per resume
                        if not any(s.get("key") == key
                                   and s.get("class") == prune_mod.HBM_OOM
                                   for s in journal["skipped"]):
                            skip = prune_mod.Skip(
                                c, prune_mod.HBM_OOM, reason,
                                hbm_source="measured")
                            journal["skipped"].append(skip.journal_record())
                            commit_json(path, journal)
                        print_fn(f"rung {rung}: {key} skipped without a "
                                 f"run (hbm-oom, measured): {reason}")
                        continue
                print_fn(f"rung {rung} ({batches} steps): {key}")
                rec = runner(c, rung, batches)
                # provenance: how long was THIS record measured (the
                # registry row must not claim the final rung's length
                # for a candidate cut earlier)
                rec.setdefault("measured_batches", batches)
                meas[str(rung)] = rec
                journal["spent_s"] = round(
                    journal["spent_s"] + float(rec.get("wall_s", 0.0)), 1)
                commit_json(path, journal)
                s = runner_mod.score(rec)
                print_fn(f"  -> score {s:.2f}"
                         + (f" ({rec['error']})" if rec.get("error")
                            else f" ({rec.get('per_chip', 0.0):.1f}/chip"
                                 + (f", goodput {rec['goodput']:.0%}"
                                    if rec.get("goodput") is not None
                                    else "") + ")"))
            measured.append((key, rec))
        if exhausted:
            journal["status"] = "budget-exhausted"
            break
        ranked = sorted(measured,
                        key=lambda kr: runner_mod.score(kr[1]),
                        reverse=True)
        ranked = [kr for kr in ranked if runner_mod.score(kr[1]) > 0]
        if not ranked:
            journal["status"] = "all-failed"
            journal["survivors"] = []
            break
        keep = max(1, int(len(ranked) * settings.keep_frac))
        survivors = [k for k, _ in ranked[:keep]]
        journal["rungs"].append({"rung": rung, "batches": batches,
                                 "measured": [k for k, _ in measured],
                                 "kept": survivors})
        journal["survivors"] = survivors
        commit_json(path, journal)
        rung += 1
        if len(survivors) == 1:
            break

    # best = top scorer at the DEEPEST rung anyone reached — the
    # halving's actual winner.  Comparing scores across rung depths
    # would let a noisy short-rung measurement of an eliminated
    # candidate beat the steady-state winner.  Only if every
    # deepest-rung measurement failed does the next-shallower rung
    # compete (mid-rung budget exhaustion).
    deepest_rung = -1
    for meas in journal["measurements"].values():
        if meas:
            deepest_rung = max(deepest_rung,
                               max(int(r) for r in meas))
    best_key, best_rec, best_score = None, None, 0.0
    for r in range(deepest_rung, -1, -1):
        for key, meas in journal["measurements"].items():
            rec = meas.get(str(r))
            if rec is None:
                continue
            s = runner_mod.score(rec)
            if s > best_score:
                best_key, best_rec, best_score = key, rec, s
        if best_key is not None:
            break
    if best_key is not None:
        journal["best"] = {
            "key": best_key,
            "overrides": journal["candidates"][best_key]["overrides"],
            "base": journal["candidates"][best_key]["base"],
            "score": round(best_score, 3),
            "record": best_rec,
        }
    if journal["status"] == "running":
        journal["status"] = "complete"
    commit_json(path, journal)
    if journal["best"] is not None:
        print_fn(f"best: {journal['best']['key']} "
                 f"(score {journal['best']['score']:.2f}, "
                 f"status {journal['status']}, "
                 f"{journal['spent_s']:.0f}s/"
                 f"{journal['budget_s']:.0f}s budget)")
    else:
        print_fn(f"no successful measurement (status {journal['status']})")
    return journal


def _candidate_from_journal(model: str, journal: dict,
                            key: str) -> Candidate:
    """Rebuild a Candidate from its journaled overrides (a resumed
    search whose space enumeration changed still honors the journal)."""
    rec = journal["candidates"][key]
    return Candidate.make(model, dict(rec["overrides"]),
                          dict(rec["base"]),
                          workload=journal.get("workload", "train"))
