"""The tunable levers per zoo member, and the seeded best-known configs.

A *candidate* is one assignment of the levers BASELINE.md's manual
sweeps actually moved:

- ``batch_size`` — power-of-two ladder around the seeded batch
- ``gradient_accumulation_steps`` — 1..64 (microbatching without
  remat's recompute)
- ``accum_dtype`` — f32 (exact mean) vs bf16 (the HBM lever)
- ``gradient_checkpointing`` — remat: FLOPs for activation HBM
- ``scan_layers`` — one compiled layer body (decoder families)
- ``fusion_threshold_bytes`` — the allreduce combine threshold
- ``variable_update`` — psum vs the zero1 sharded-optimizer arm

Per-member validity rules are structural (accum must divide the batch,
the dtype lever needs accum > 1, remat needs a transformer, scan needs
a decoder); everything deeper — the zero1 composition matrix, the
eval/forward-only exclusions — is enforced by ``BenchmarkConfig
.resolve()`` and handled by the pruner as a free flag-time skip.

``SEED_CONFIGS`` is the machine-readable form of the BASELINE.md zoo
table's best-known single-chip configs.  It used to live as
``DEFAULT_MATRIX``/``EXTRA_FLAGS`` in ``scripts/sweep_zoo.py``; the
sweep now imports it from here so the sweep, the tuner, and the HBM
model all share one copy of that knowledge.
"""

from __future__ import annotations

import dataclasses

from tpu_hc_bench.flags import (
    DEFAULT_FUSION_THRESHOLD_BYTES,
    BenchmarkConfig,
)

__all__ = [
    "Candidate", "SEED_CONFIGS", "seed_candidate", "member_space",
    "seed_matrix", "seed_extra_flags", "LEVERS",
    "SERVE_LEVERS", "SEED_SERVE_CONFIGS", "serve_seed_candidate",
    "serve_member_space",
]

# The lever fields a candidate may override (everything else rides the
# member's base flags or the BenchmarkConfig defaults).
LEVERS = (
    "batch_size",
    "gradient_accumulation_steps",
    "accum_dtype",
    "gradient_checkpointing",
    "scan_layers",
    "fusion_threshold_bytes",
    "variable_update",
)

# The serving lane's levers (round 16, ``tpu_hc_bench.serve``): the
# decode bucket ladder, the continuous-batching admission cap, and the
# paged-KV geometry; round 18 adds the decode-kernel arm (gather
# reference vs the Pallas paged flash-decode kernel), its page-block
# size, and the quantization arm — kernels are autotuned like any
# other lever.  All are BenchmarkConfig fields, so the halving search,
# the journal, and ``--config=auto`` handle serve candidates with the
# same machinery — a serve candidate just carries ``workload="serve"``
# so the pruner's flag-time resolve() runs the serving validity
# matrix, and its registry row is keyed ``<model>@serve`` (one member
# can hold a tuned row per lane).
SERVE_LEVERS = (
    "serve_buckets",
    "max_in_flight",
    "kv_page_size",
    "kv_pages",
    "decode_attention",
    "quant",
    "decode_block_pages",
    # round 25: lazy reservation + prefix sharing — admission policy
    # is a lever like any kernel arm (resolve() enforces the
    # prefix_cache->lazy dependency at flag time, so the pruner never
    # runs an invalid pairing)
    "kv_reserve",
    "prefix_cache",
    "kv_growth_headroom",
)

# member -> best-known single-chip config (BASELINE.md zoo table).
# "batch" is the per-chip batch; "accum"/"accum_dtype" the microbatch
# levers; "base" the member-fixed flags the search does not move
# (attention kernel choice).  The accumulation members' batches exceed
# HBM as plain one-shot batches and fit only as accum microbatches —
# that pairing seeds the pruner's HBM model.
SEED_CONFIGS: dict[str, dict] = {
    "trivial":          {"batch": 512},
    "lenet":            {"batch": 2048},
    "alexnet":          {"batch": 2048, "accum": 4},
    "overfeat":         {"batch": 4096, "accum": 16},
    "googlenet":        {"batch": 256},
    "mobilenet":        {"batch": 256},
    "nasnet":           {"batch": 128},
    "nasnetlarge":      {"batch": 128, "accum": 8},
    "densenet40_k12":   {"batch": 512},
    "densenet100_k12":  {"batch": 256},
    "resnet18":         {"batch": 256},
    "resnet34":         {"batch": 256},
    "resnet50":         {"batch": 128},
    "resnet101":        {"batch": 512, "accum": 8},
    "resnet152":        {"batch": 512, "accum": 8},
    "resnet50_v2":      {"batch": 1024, "accum": 8},
    "resnet101_v2":     {"batch": 512, "accum": 8},
    "resnet152_v2":     {"batch": 512, "accum": 8},
    "resnet20_cifar":   {"batch": 1024},
    "resnet56_cifar":   {"batch": 512},
    "resnet110_cifar":  {"batch": 256},
    "vgg11":            {"batch": 1024, "accum": 8},
    "vgg16":            {"batch": 1024, "accum": 8},
    "vgg19":            {"batch": 1024, "accum": 8},
    "inception3":       {"batch": 128},
    "vit_b16":          {"batch": 256, "accum": 4},
    "vit_l16":          {"batch": 512, "accum": 8},
    "inception4":       {"batch": 512, "accum": 8},
    "bert_base":        {"batch": 1024, "accum": 8},
    "bert_large":       {"batch": 1024, "accum": 32},
    "gpt2":             {"batch": 128, "accum": 8,
                         "base": {"attention_impl": "flash"}},
    "gpt2_medium":      {"batch": 64, "accum": 16,
                         "base": {"attention_impl": "flash"}},
    # round 5: the bf16 accumulator unlocked batch scaling past the
    # bs=16 OOM wall (microbatch 8; BASELINE.md round 5) — +37%
    "gpt2_moe":         {"batch": 512, "accum": 64, "accum_dtype": "bf16",
                         "base": {"attention_impl": "flash"}},
    "llama_1b":         {"batch": 2,
                         "base": {"attention_impl": "flash"}},
    # round 4: both members' old tf_cnn-default batches starved the
    # chip — these are the measured TPU operating points
    "ncf":              {"batch": 1048576},
    "deepspeech2":      {"batch": 256},
}

# member -> best-known SERVING config (the serve lane's seed points;
# decoder members only — classify members serve single-forward requests
# whose only lever is the batch-bucket cap).  Values are starting
# points, not measurements: BASELINE.md grows a "Serving" table as the
# serve searches land.
SEED_SERVE_CONFIGS: dict[str, dict] = {
    "trivial":      {"max_in_flight": 8},
    "moe_tiny":     {"max_in_flight": 8},
    "llama_tiny":   {"max_in_flight": 8},
    "gpt2":         {"max_in_flight": 16},
    "gpt2_medium":  {"max_in_flight": 8},
    "gpt2_moe":     {"max_in_flight": 8},
    "llama_1b":     {"max_in_flight": 4},
}

_KV_PAGE_LADDER = (8, 16, 32)
_DECODE_BLOCK_PAGES_LADDER = (2, 4)

_ACCUM_LADDER = (1, 2, 4, 8, 16, 32, 64)
_FUSION_LADDER = (DEFAULT_FUSION_THRESHOLD_BYTES,
                  DEFAULT_FUSION_THRESHOLD_BYTES // 4)

_CONFIG_DEFAULTS = {f.name: f.default
                    for f in dataclasses.fields(BenchmarkConfig)}


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in a member's search space.

    ``overrides`` maps BenchmarkConfig field names to lever values;
    ``base`` carries the member-fixed flags the search does not move
    (e.g. ``attention_impl=flash`` for the decoder families).
    ``workload`` selects the lane — a ``"serve"`` candidate draws from
    ``SERVE_LEVERS`` and resolves under the serving validity matrix.
    """

    model: str
    overrides: tuple[tuple[str, object], ...]   # sorted, hashable
    base: tuple[tuple[str, object], ...] = ()
    workload: str = "train"

    @staticmethod
    def make(model: str, overrides: dict, base: dict | None = None,
             workload: str = "train") -> "Candidate":
        levers = SERVE_LEVERS if workload == "serve" else LEVERS
        for k in overrides:
            if k not in levers:
                raise ValueError(
                    f"not a tunable lever ({workload} lane): {k!r}")
        return Candidate(
            model=model,
            overrides=tuple(sorted(overrides.items())),
            base=tuple(sorted((base or {}).items())),
            workload=workload,
        )

    @property
    def key(self) -> str:
        """Stable identity for journal/registry bookkeeping."""
        parts = [f"{k}={v}" for k, v in self.overrides]
        return ",".join(parts) or "defaults"

    @property
    def batch_size(self) -> int:
        d = dict(self.overrides)
        return int(d.get("batch_size", _CONFIG_DEFAULTS["batch_size"]))

    def all_overrides(self) -> dict:
        """base + lever overrides, one dict (base first: a lever that
        shadows a base flag wins)."""
        out = dict(self.base)
        out.update(dict(self.overrides))
        return out

    def to_config(self, **extra) -> BenchmarkConfig:
        """An UNresolved BenchmarkConfig with this candidate applied
        (the pruner calls ``.resolve()`` on it to get flag-time
        rejections for free — serve candidates under the serving
        validity matrix)."""
        kwargs = dict(self.all_overrides())
        kwargs.setdefault("workload", self.workload)
        kwargs.update(extra)
        return BenchmarkConfig(model=self.model, **kwargs)

    def to_flags(self) -> list[str]:
        """The candidate as driver CLI flags (batch rides the
        positional contract, so it is excluded here)."""
        out = []
        for k, v in {**dict(self.base), **dict(self.overrides)}.items():
            if k == "batch_size":
                continue
            if isinstance(v, bool):
                v = "True" if v else "False"
            out.append(f"--{k}={v}")
        return sorted(out)


def seed_candidate(model: str) -> Candidate:
    """The member's seeded best-known config as a Candidate (identity
    point of the search space; also the HBM model's anchor)."""
    seed = SEED_CONFIGS.get(model)
    if seed is None:
        raise ValueError(
            f"no seeded config for {model!r} (not a sweep-matrix member); "
            f"pass an explicit space")
    overrides: dict = {"batch_size": seed["batch"]}
    if seed.get("accum", 1) > 1:
        overrides["gradient_accumulation_steps"] = seed["accum"]
    if seed.get("accum_dtype"):
        overrides["accum_dtype"] = seed["accum_dtype"]
    return Candidate.make(model, overrides, seed.get("base"))


def _pow2_ladder(center: int, down: int = 2, up: int = 2) -> list[int]:
    """Power-of-two ladder around ``center``: center/2^down ..
    center*2^up, floored at 1."""
    out = []
    for e in range(-down, up + 1):
        v = center * (2 ** e) if e >= 0 else center // (2 ** -e)
        if v >= 1 and v not in out:
            out.append(int(v))
    return out


def _member_decodes(model: str) -> bool:
    """True for causal-LM members (the serve lane's decode families);
    best-effort like ``_member_levers`` so the module stays importable
    without the models package."""
    try:
        from tpu_hc_bench.models import get_model_spec

        return bool(get_model_spec(model).causal_lm)
    except Exception:
        return False


def _member_levers(model: str) -> dict[str, bool]:
    """Which structural levers this member supports (remat needs a
    transformer, scan a decoder family).  Spec lookup is best-effort so
    the space module stays importable without the models package."""
    try:
        from tpu_hc_bench.models import get_model_spec

        spec = get_model_spec(model)
        return {"remat": bool(spec.attention or spec.is_text),
                "scan": bool(spec.causal_lm)}
    except Exception:
        return {"remat": False, "scan": False}


def member_space(model: str, mode: str = "axes",
                 seed: Candidate | None = None) -> list[Candidate]:
    """Enumerate the member's candidates, seed first.

    ``mode="axes"`` (default) is the manual-sweep shape automated: vary
    ONE lever at a time off the seeded best-known config — the batch
    ladder, the accum ladder, the dtype/remat/scan/fusion/arm toggles.
    ``mode="grid"`` crosses batch x accum x dtype for members where the
    interaction matters (the OOM-wall members), still toggling the
    remaining levers axis-wise.  Structurally invalid points (accum not
    dividing batch, dtype lever without accum) are never generated;
    deeper validity is the pruner's job.
    """
    if mode not in ("axes", "grid"):
        raise ValueError(f"mode must be axes|grid: {mode!r}")
    seed = seed or seed_candidate(model)
    levers = _member_levers(model)
    sd = dict(seed.overrides)
    base = dict(seed.base)
    seed_batch = int(sd.get("batch_size", _CONFIG_DEFAULTS["batch_size"]))
    seed_accum = int(sd.get("gradient_accumulation_steps", 1))

    out: list[Candidate] = [seed]
    seen = {seed.key}

    def add(overrides: dict):
        # structural validity: accum divides batch, microbatch >= 1,
        # the dtype lever only exists with accum > 1
        b = int(overrides.get("batch_size", seed_batch))
        a = int(overrides.get("gradient_accumulation_steps", 1))
        if a > 1 and (b % a or b // a < 1):
            return
        if overrides.get("accum_dtype", "f32") != "f32" and a <= 1:
            return
        c = Candidate.make(model, overrides, base)
        if c.key not in seen:
            seen.add(c.key)
            out.append(c)

    def vary(**delta):
        o = dict(sd)
        for k, v in delta.items():
            if v is None:
                o.pop(k, None)
            else:
                o[k] = v
        # normalize: accum==1 and f32 are the defaults, drop them so
        # equal configs get equal keys
        if o.get("gradient_accumulation_steps") == 1:
            o.pop("gradient_accumulation_steps", None)
            o.pop("accum_dtype", None)
        if o.get("accum_dtype") == "f32":
            o.pop("accum_dtype", None)
        add(o)

    batches = _pow2_ladder(seed_batch)
    accums = [a for a in _ACCUM_LADDER if a != seed_accum]

    if mode == "grid":
        dtypes = ("f32", "bf16")
        for b in batches:
            for a in _ACCUM_LADDER:
                for dt in dtypes:
                    vary(batch_size=b,
                         gradient_accumulation_steps=a if a > 1 else None,
                         accum_dtype=dt if a > 1 else None)
    else:
        for b in batches:
            vary(batch_size=b)
        for a in accums:
            vary(gradient_accumulation_steps=a if a > 1 else None)
        if seed_accum > 1:
            cur = sd.get("accum_dtype", "f32")
            vary(accum_dtype="bf16" if cur == "f32" else "f32")

    # the toggle levers are axis-wise in both modes
    if levers["remat"]:
        vary(gradient_checkpointing=True)
    if levers["scan"]:
        vary(scan_layers=True)
    for ft in _FUSION_LADDER:
        if ft != sd.get("fusion_threshold_bytes",
                        DEFAULT_FUSION_THRESHOLD_BYTES):
            vary(fusion_threshold_bytes=ft)
    vary(variable_update="zero1")
    return out


def serve_seed_candidate(model: str) -> Candidate:
    """The member's seeded serving config as a workload="serve"
    Candidate (identity point of the serve search space)."""
    seed = SEED_SERVE_CONFIGS.get(model)
    if seed is None:
        raise ValueError(
            f"no seeded serving config for {model!r} (decoder/classify "
            f"members only; see SEED_SERVE_CONFIGS)")
    overrides = {k: v for k, v in seed.items() if k != "base"}
    return Candidate.make(model, overrides, seed.get("base"),
                          workload="serve")


def serve_member_space(model: str,
                       seed: Candidate | None = None) -> list[Candidate]:
    """Enumerate the member's serving candidates, seed first (the
    axes-mode discipline of ``member_space``: one lever at a time off
    the seed).

    Levers: the admission cap (``max_in_flight`` power-of-two ladder —
    more rows per decode step vs deeper queues), the KV page size
    (coarser pages waste tail tokens, finer pages widen the gather
    tables), the pool size (auto vs a half pool — queueing for pages vs
    HBM held), the bucket ladder shape (the full power-of-two
    ladder vs one top-bucket — per-compile cost vs padding waste), and
    — decoder members only (round 18) — the decode-kernel arms: the
    paged Pallas flash-decode kernel vs the gather reference, its
    page-block size, and the int8 weight/KV quantization arms.
    Structurally-coupled levers are generated together (``int8_kv``
    and ``decode_block_pages`` only exist on the paged arm — the
    combinations ``resolve()`` would reject are never emitted);
    validity beyond this is ``resolve()``'s serving matrix, reached by
    the pruner's flag-time check.
    """
    seed = seed or serve_seed_candidate(model)
    if seed.workload != "serve":
        raise ValueError(f"serve_member_space needs a serve-lane seed: "
                         f"{seed.workload!r}")
    sd = dict(seed.overrides)
    base = dict(seed.base)
    cap = int(sd.get("max_in_flight", _CONFIG_DEFAULTS["max_in_flight"]))
    page = int(sd.get("kv_page_size", _CONFIG_DEFAULTS["kv_page_size"]))

    out: list[Candidate] = [seed]
    seen = {seed.key}

    def vary(**delta):
        o = dict(sd)
        for k, v in delta.items():
            if v is None:
                o.pop(k, None)
            else:
                o[k] = v
        c = Candidate.make(model, o, base, workload="serve")
        if c.key not in seen:
            seen.add(c.key)
            out.append(c)

    for m in _pow2_ladder(cap, down=1, up=2):
        vary(max_in_flight=m)
    for p in _KV_PAGE_LADDER:
        if p != page:
            vary(kv_page_size=p)
    # decode-kernel arms (decoder members only; classify members have
    # no decode step for these to shape)
    if _member_decodes(model):
        vary(decode_attention="paged")
        for ppb in _DECODE_BLOCK_PAGES_LADDER:
            vary(decode_attention="paged", decode_block_pages=ppb)
        vary(quant="int8_w")
        # int8_kv's per-page scales are consumed inside the paged
        # kernel, so the arm only exists there
        vary(decode_attention="paged", quant="int8_kv")
    # one top bucket: a single compiled decode shape, every step padded
    # to the cap (the compile-count-vs-padding tradeoff made explicit)
    vary(serve_buckets=str(cap))
    # half pool: enough pages for cap/2 worst-case requests + the trash
    # page — admission blocks on pages instead of slots (queueing-for-
    # memory, the vLLM regime), trading HBM held for queue delay
    max_ctx = (_CONFIG_DEFAULTS["max_prompt_len"]
               + _CONFIG_DEFAULTS["max_output_len"])
    width = -(-max_ctx // page)
    half = 1 + max(1, cap // 2) * width
    vary(kv_pages=half)
    return out


# --- sweep_zoo.py compatibility views ---------------------------------


def seed_matrix() -> list[tuple[str, int]]:
    """(model, per-chip batch) pairs — the sweep's DEFAULT_MATRIX."""
    return [(m, cfg["batch"]) for m, cfg in SEED_CONFIGS.items()]


def seed_extra_flags(model: str) -> list[str]:
    """The member's seeded non-batch flags in CLI form — the sweep's
    old EXTRA_FLAGS entry."""
    return seed_candidate(model).to_flags()
