"""Utilities: hardware peak tables, log naming, sanity reporting."""
