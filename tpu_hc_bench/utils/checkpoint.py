"""Checkpoint/resume for TrainState (Orbax-backed).

The reference has **no** checkpointing (SURVEY.md §5: benchmark runs are
stateless 150-step measurements) — this subsystem exceeds parity so the
framework is usable for real training runs, not just benchmarks.  Layout:
one Orbax PyTree checkpoint per step under ``<dir>/step_<n>``, with
``latest_step`` discovery for resume.  Only array/step state is saved;
``apply_fn``/``tx`` are reconstructed from config at restore (standard JAX
practice — function objects don't serialize).
"""

from __future__ import annotations

import re
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_hc_bench.train.step import TrainState


def _step_dir(base: Path, step: int) -> Path:
    return base / f"step_{step:08d}"


def save(state: TrainState, directory: str | Path) -> Path:
    """Save the array state of `state` at its current step."""
    base = Path(directory)
    base.mkdir(parents=True, exist_ok=True)
    step = int(jax.device_get(state.step))
    path = _step_dir(base, step)
    payload = {
        "step": np.asarray(step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
    }
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path.resolve(), payload, force=True)
    return path


def latest_step(directory: str | Path) -> int | None:
    base = Path(directory)
    if not base.exists():
        return None
    steps = [
        int(m.group(1))
        for p in base.iterdir()
        if (m := re.fullmatch(r"step_(\d+)", p.name))
    ]
    return max(steps) if steps else None


def restore(state: TrainState, directory: str | Path,
            step: int | None = None) -> TrainState:
    """Restore into an already-constructed (template) TrainState.

    ``state`` supplies the tree structure, dtypes, and the non-serializable
    ``apply_fn``/``tx``; arrays are replaced from the checkpoint.
    """
    base = Path(directory)
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base}")
    template = {
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
    }
    ckptr = ocp.PyTreeCheckpointer()
    payload = ckptr.restore(_step_dir(base, step).resolve(), item=template)
    return state.replace(
        step=jax.numpy.asarray(payload["step"], dtype=jax.numpy.int32),
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
    )
