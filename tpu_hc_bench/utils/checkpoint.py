"""Checkpoint/resume for TrainState (Orbax-backed).

The reference has **no** checkpointing (SURVEY.md §5: benchmark runs are
stateless 150-step measurements) — this subsystem exceeds parity so the
framework is usable for real training runs, not just benchmarks.  Layout:
one Orbax PyTree checkpoint per step under ``<dir>/step_<n>``, with
``latest_step`` discovery for resume.  Only array/step state is saved;
``apply_fn``/``tx`` are reconstructed from config at restore (standard JAX
practice — function objects don't serialize).
"""

from __future__ import annotations

import re
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_hc_bench.train.step import TrainState


def _step_dir(base: Path, step: int) -> Path:
    return base / f"step_{step:08d}"


def save(state: TrainState, directory: str | Path,
         sharded: bool = False) -> Path:
    """Save the array state of `state` at its current step.

    ``sharded=True`` (multi-host model-sharded states): the LIVE
    ``jax.Array``s are handed to Orbax, which writes each process's
    addressable shards and synchronizes internally — every process must
    call.  Default (host) mode device_gets first, which requires the
    state to be fully addressable (replicated or single-process).
    """
    base = Path(directory)
    base.mkdir(parents=True, exist_ok=True)
    step = int(jax.device_get(state.step))
    path = _step_dir(base, step)
    pull = (lambda t: t) if sharded else jax.device_get
    payload = {
        "step": np.asarray(step),
        "params": pull(state.params),
        "batch_stats": pull(state.batch_stats),
        "opt_state": pull(state.opt_state),
    }
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path.resolve(), payload, force=True)
    return path


def latest_step(directory: str | Path) -> int | None:
    base = Path(directory)
    if not base.exists():
        return None
    steps = [
        int(m.group(1))
        for p in base.iterdir()
        if (m := re.fullmatch(r"step_(\d+)", p.name))
    ]
    return max(steps) if steps else None


def restore(state: TrainState, directory: str | Path,
            step: int | None = None, sharded: bool = False) -> TrainState:
    """Restore into an already-constructed (template) TrainState.

    ``state`` supplies the tree structure, dtypes, and the non-serializable
    ``apply_fn``/``tx``; arrays are replaced from the checkpoint.

    ``sharded=True``: ``state`` must already be PLACED on the mesh (its
    arrays carry shardings); Orbax restores each array with that
    sharding, every process reading only the shards it addresses —
    the multi-host restore for model-sharded states.
    """
    base = Path(directory)
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base}")
    pull = (lambda t: t) if sharded else jax.device_get
    template = {
        "step": jax.device_get(state.step),
        "params": pull(state.params),
        "batch_stats": pull(state.batch_stats),
        "opt_state": pull(state.opt_state),
    }
    restore_args = None
    if sharded:
        def as_restore_args(x):
            return ocp.ArrayRestoreArgs(sharding=x.sharding,
                                        global_shape=x.shape,
                                        dtype=x.dtype)
        restore_args = {
            k: (ocp.RestoreArgs() if k == "step"
                else jax.tree.map(as_restore_args, template[k]))
            for k in template
        }
    ckptr = ocp.PyTreeCheckpointer()
    payload = ckptr.restore(_step_dir(base, step).resolve(), item=template,
                            restore_args=restore_args)
    return state.replace(
        step=jax.numpy.asarray(payload["step"], dtype=jax.numpy.int32),
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
    )


def save_pp(params, opt_state, step: int, directory: str | Path) -> Path:
    """Multi-host PP checkpoint: the PP-NATIVE stacked layout, sharded.

    The DP<->PP checkpoint interchange (pipeline.pp_state_from_train_state)
    needs fully addressable arrays, which a multi-host pipe-sharded trunk
    is not — so multi-host PP saves the state AS IT IS SHARDED: the
    ``[L, ...]`` stacked trunk's LIVE jax.Arrays go straight to Orbax and
    every process writes only its addressable shards (round-4 closure of
    the driver's multi-host-PP --train_dir rejection).  Layout:
    ``<dir>/step_<n>/{pp_params,opt_state}``.  NOT interchangeable with
    the DP-layout checkpoints `save` writes (different tree: ``trunk`` vs
    ``layer_i``; a cross-restore fails loudly on structure mismatch) —
    but the stacked GLOBAL shapes are pipe-degree independent, so a
    PP-native checkpoint restores under any pipe degree whose mesh can
    place it.  ALL processes must call (Orbax barriers internally).
    ``opt_state=None`` saves params only.
    """
    base = Path(directory)
    base.mkdir(parents=True, exist_ok=True)
    path = _step_dir(base, int(step))
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save((path / "pp_params").resolve(), params, force=True)
    if opt_state is not None:
        ckptr.save((path / "opt_state").resolve(), opt_state, force=True)
    return path


def restore_pp(params, opt_state, directory: str | Path,
               step: int | None = None):
    """Restore a PP-native checkpoint into PLACED templates.

    ``params``/``opt_state`` must already be placed on the mesh (their
    arrays carry the pipe/model shardings); each array restores with its
    committed sharding, every process reading only the shards it
    addresses.  ``opt_state=None`` restores params only (forward-only
    eval never places the momentum trace).  Returns
    ``(params, opt_state, step)``.
    """
    base = Path(directory)
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base}")
    path = _step_dir(base, step)

    def args_of(tree):
        return jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding,
                                           global_shape=x.shape,
                                           dtype=x.dtype), tree)

    ckptr = ocp.PyTreeCheckpointer()
    params = ckptr.restore((path / "pp_params").resolve(), item=params,
                           restore_args=args_of(params))
    if opt_state is not None:
        opt_state = ckptr.restore((path / "opt_state").resolve(),
                                  item=opt_state,
                                  restore_args=args_of(opt_state))
    return params, opt_state, step
