"""Checkpoint/resume for TrainState (Orbax-backed).

The reference has **no** checkpointing (SURVEY.md §5: benchmark runs are
stateless 150-step measurements) — this subsystem exceeds parity so the
framework is usable for real training runs, not just benchmarks.  Layout:
one Orbax PyTree checkpoint per step under ``<dir>/step_<n>``, with
``latest_step`` discovery for resume.  Only array/step state is saved;
``apply_fn``/``tx`` are reconstructed from config at restore (standard JAX
practice — function objects don't serialize).

Crash-safe commit protocol (round 8): a save writes into
``step_<n>.tmp``, then renames to ``step_<n>``, then drops a
``step_<n>.complete`` sentinel next to the directory.  Discovery
(``latest_step``/``complete_steps``) only believes sentineled steps, so
a crash mid-save leaves either an ignored ``.tmp`` or an ignored
sentinel-less directory — never a "latest" checkpoint that ``restore``
then chokes on; ``restore(step=None)`` therefore falls back to the
newest *complete* step automatically.  ``gc_checkpoints`` is the
``--keep_checkpoints=N`` retention pass (newest N complete steps
survive; stale ``.tmp``/sentinel-less debris is reaped).

Sharded optimizer state (``--variable_update=zero1``, round 6): the
opt-state leaves are stacked ``[N, k]`` arrays sharded over the data
axis.  Single-process saves go through the normal host path — the
``device_get`` in ``snapshot_to_host`` GATHERS the shards (gather-on-
save, manifest-noted by the driver), so the on-disk layout is the
plain stacked array and ``restore`` into a ``make_zero1_state``
template + ``place_zero1_state`` round-trips bitwise.  Multi-host
zero1 states are NOT host-addressable and take the ``sharded=True``
Orbax path (restore after placement), exactly like the TP/EP states.
The layout depends only on param shapes and N — not on the fusion
threshold — but a zero1 checkpoint is not interchangeable with a
psum/replicated one (different opt-state tree; the structure mismatch
fails loudly at restore).

Topology sidecars + elastic restore (round 12): every save records a
small ``step_<n>.topology.json`` next to the commit sentinel — world
size, mesh shape, variable-update arm, PP degree, on-disk layout,
dtype policy (``topology.topology_record``).  ``restore`` validates it
against the live topology (``expect_topology``) and raises ONE loud
:class:`TopologyMismatchError` naming both sides instead of the opaque
Orbax sharding error a mismatched restore used to die with.
``restore_elastic`` is the reshape path (``--resume=elastic``):
host-layout replicated trees drop straight onto the new mesh, and
zero1's gathered ``[N, k]`` optimizer shards are resplit to the new
world size (``train.step.resplit_zero1_opt``) before placement.  The
compatibility matrix is ``topology.elastic_plan``.

Async saves (round 10): a synchronous ``save`` blocks the step loop
for snapshot + Orbax write + fsync + commit, but only the *snapshot*
actually needs the step loop stopped — the write targets host memory
the device no longer owns.  ``AsyncCheckpointWriter`` splits the save
there: ``submit`` snapshots device arrays to host (per-leaf
``copy_to_host_async`` so the transfers overlap, then one gather) and
hands the payload to a bounded background thread that runs the SAME
tmp→rename→sentinel commit protocol.  At most one save is in flight;
``wait()`` is the barrier (before the next save, GC, restore, and
exit) and the place a background write error re-raises on the main
thread.  A crash mid-write leaves an uncommitted ``.tmp``/sentinel-
less dir that discovery already ignores — the async path adds no new
failure modes to the commit protocol.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import re
import shutil
import threading
import time
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_hc_bench.obs import timeline as timeline_mod
from tpu_hc_bench.train.step import TrainState

_STEP_RE = re.compile(r"step_(\d+)")


class TopologyMismatchError(ValueError):
    """A checkpoint's recorded topology does not fit the live one (and
    the caller did not ask for — or the transition does not support —
    an elastic reshape)."""


def _step_dir(base: Path, step: int) -> Path:
    return base / f"step_{step:08d}"


def _marker(base: Path, step: int) -> Path:
    """The completion sentinel: ``step_<n>.complete`` NEXT TO the step
    directory (inside it would pollute the Orbax tree)."""
    return base / f"step_{step:08d}.complete"


def _topology_sidecar(base: Path, step: int) -> Path:
    """The topology sidecar: ``step_<n>.topology.json`` next to the
    sentinel (same placement rationale)."""
    return base / f"step_{step:08d}.topology.json"


def _fsync_path(path: Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass    # not every filesystem supports directory fsync


def _marker_id(marker: Path) -> tuple | None:
    """Identity of an existing sentinel file (None when absent) — a
    fresh commit rewrites the file, so (inode, mtime_ns) distinguishes
    the new sentinel from a stale one left by an earlier save of the
    SAME step (a rewound/resumed run re-saving its restore point)."""
    try:
        st = marker.stat()
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns)


def _commit_step_dir(base: Path, step: int, tmp: Path,
                     stale_id: tuple | None = None,
                     topology: dict | None = None) -> Path:
    """tmp dir -> final dir -> sentinel, each durably ordered.
    ``topology`` (when given) is written as the ``step_<n>.topology.json``
    sidecar BEFORE the sentinel, so a complete checkpoint always carries
    its sidecar; a topology-less re-save of the same step removes any
    stale sidecar instead of leaving one that lies.

    The prior sentinel (if any) is only touched HERE, after the full
    Orbax write landed in ``tmp`` — a crash during the long write
    leaves the previous complete checkpoint fully intact and
    discoverable.  Multi-process: Orbax has already barriered all
    writers inside ``save``; process 0 performs the single
    retract+rename+sentinel, the others wait for a sentinel *different
    from* ``stale_id`` (captured before the save) to appear on the
    shared filesystem, so a stale marker never releases them early.
    """
    final = _step_dir(base, step)
    marker = _marker(base, step)
    if jax.process_count() > 1 and jax.process_index() != 0:
        deadline = time.monotonic() + 60.0
        while _marker_id(marker) in (None, stale_id):
            if time.monotonic() > deadline:
                raise OSError(
                    f"checkpoint commit sentinel {marker} never appeared "
                    "(is --train_dir on a filesystem shared by all "
                    "hosts?)")
            time.sleep(0.05)
        return final
    _fsync_path(tmp)
    marker.unlink(missing_ok=True)
    if final.exists():
        shutil.rmtree(final)
    os.replace(tmp, final)
    side = _topology_sidecar(base, step)
    if topology is not None:
        with open(side, "w") as f:
            json.dump(topology, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
    else:
        side.unlink(missing_ok=True)
    with open(marker, "w") as f:
        f.write("ok\n")
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(base)
    return final


def snapshot_to_host(state: TrainState) -> tuple[int, dict]:
    """Snapshot the array state to host memory: ``(step, payload)``.

    This is the only part of a save that must block the step loop.
    Every leaf's device→host copy is *started* first
    (``copy_to_host_async``) so the transfers run concurrently; the
    ``device_get`` gather then mostly finds bytes already landed.
    Requires a fully-addressable state (replicated or single-process) —
    the same contract as host-mode ``save``.
    """
    step = int(jax.device_get(state.step))
    trees = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }
    with timeline_mod.span("ckpt_snapshot", step=step):
        for leaf in jax.tree.leaves(trees):
            if isinstance(leaf, jax.Array):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass    # backend without async copies: the gather pays
        payload: dict = {"step": np.asarray(step)}
        for name, tree in trees.items():
            payload[name] = jax.device_get(tree)
    return step, payload


def write_host_payload(payload: dict, directory: str | Path,
                       step: int, topology: dict | None = None) -> Path:
    """Orbax-write a payload under the commit protocol (tmp dir →
    rename → topology sidecar → sentinel).  The payload is host arrays
    (the async writer's snapshot — pure host/filesystem work, safe off
    the main thread) or live ``jax.Array``s (the sharded path: Orbax
    writes each process's addressable shards and synchronizes
    internally)."""
    base = Path(directory)
    base.mkdir(parents=True, exist_ok=True)
    tmp = base / (_step_dir(base, step).name + ".tmp")
    stale_id = _marker_id(_marker(base, step))
    # span-recorded (obs.timeline): from the writer thread this shows as
    # the overlapped write lane; from the main thread, the blocking one
    with timeline_mod.span("ckpt_write", step=step):
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(tmp.resolve(), payload, force=True)
        return _commit_step_dir(base, step, tmp, stale_id,
                                topology=topology)


def save(state: TrainState, directory: str | Path,
         sharded: bool = False, topology: dict | None = None) -> Path:
    """Save the array state of `state` at its current step.

    ``sharded=True`` (multi-host model-sharded states): the LIVE
    ``jax.Array``s are handed to Orbax, which writes each process's
    addressable shards and synchronizes internally — every process must
    call.  Default (host) mode device_gets first, which requires the
    state to be fully addressable (replicated or single-process).
    ``topology``: the elastic-resume sidecar record
    (``topology.topology_record``), committed next to the sentinel.
    """
    if sharded:
        step = int(jax.device_get(state.step))
        payload = {
            "step": np.asarray(step),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
    else:
        step, payload = snapshot_to_host(state)
    return write_host_payload(payload, directory, step, topology=topology)


class AsyncCheckpointWriter:
    """Bounded background checkpoint writer: in-flight ≤ 1.

    ``submit`` barriers on the previous save, snapshots the state to
    host (the only blocking span), and hands the payload to a daemon
    thread that runs ``write_host_payload`` (Orbax write + fsync +
    rename + sentinel) and, when asked, the retention GC — all off the
    step loop.  ``wait()`` is the barrier the driver runs before GC,
    restore, emergency saves, and exit; a background write error is
    captured and re-raised there, on the main thread, with the writer-
    thread traceback attached.

    Single-process only by design: multi-host saves are COLLECTIVE
    (Orbax barriers every writer, then non-zero processes wait on the
    commit sentinel), and a collective running on a background thread
    on some hosts while others have already moved on is a deadlock —
    the driver keeps multi-host, PP-native, and sharded saves on the
    synchronous path.

    ``commits`` is a thread-safe queue of landed-save records
    (``{"step", "write_s", "path"}``) the driver drains into the
    metrics stream from the main thread (MetricsWriter is not
    thread-safe, so the writer thread never touches it).
    """

    def __init__(self, directory: str | Path, print_fn=None):
        self._dir = Path(directory)
        self._print = print_fn or (lambda s: None)
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._error_tb = None
        self.commits: collections.deque = collections.deque()

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def submit(self, state: TrainState, gc_keep: int = 0,
               topology: dict | None = None) -> int:
        """Barrier on the previous save, snapshot, hand off.  Returns
        the snapshotted step.  Blocking cost: the previous write's
        remaining tail (usually zero — one save per sync window leaves
        a whole window to finish) plus the device→host snapshot."""
        self.wait()
        step, payload = snapshot_to_host(state)
        self._thread = threading.Thread(
            target=self._write, args=(step, payload, gc_keep, topology),
            name=f"tpu-hc-bench-ckpt-writer-{step}", daemon=True)
        self._thread.start()
        return step

    def _write(self, step: int, payload: dict, gc_keep: int,
               topology: dict | None = None) -> None:
        from tpu_hc_bench.resilience.retry import retry_io

        t0 = time.monotonic()
        try:
            # same transient-I/O budget as the synchronous save path
            # (driver.save_now's retry_io): an NFS/GCS blip must not
            # surface at the next barrier as a run-killing error.
            # Single-process by construction, so retrying is safe
            # (multi-host saves never take the async path).
            path = retry_io(
                lambda: write_host_payload(payload, self._dir, step,
                                           topology=topology),
                what="async checkpoint write", print_fn=self._print)
            if gc_keep:
                # no writer= here: the GC runs ON the writer thread,
                # strictly after its own commit landed
                gc_checkpoints(self._dir, gc_keep, print_fn=self._print)
            dt = time.monotonic() - t0
            self.commits.append(
                {"step": step, "write_s": round(dt, 4), "path": str(path)})
            self._print(f"checkpoint saved: {path} "
                        f"(async write {dt:.2f}s, overlapped)")
        except BaseException as e:
            self._error = e
            self._error_tb = e.__traceback__

    def wait(self) -> None:
        """Barrier: join any in-flight write; re-raise its error here."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            exc, self._error = self._error, None
            if hasattr(exc, "add_note"):
                exc.add_note(
                    "raised in the async checkpoint writer thread; "
                    "re-raised at the next barrier "
                    "(utils.checkpoint.AsyncCheckpointWriter.wait)")
            raise exc.with_traceback(self._error_tb)


def complete_steps(directory: str | Path) -> list[int]:
    """Ascending step numbers whose commit sentinel exists — the only
    checkpoints discovery believes (``.tmp`` and sentinel-less dirs are
    crashed saves).  A checkpoint written before the sentinel scheme can
    be adopted by hand: ``touch <dir>/step_NNNNNNNN.complete`` after
    verifying the directory restores (the driver warns when it finds
    only sentinel-less step dirs rather than silently reinitializing)."""
    base = Path(directory)
    if not base.exists():
        return []
    return sorted(
        int(m.group(1))
        for p in base.iterdir()
        if p.is_dir()
        and (m := _STEP_RE.fullmatch(p.name))
        and _marker(base, int(m.group(1))).exists()
    )


def latest_step(directory: str | Path) -> int | None:
    steps = complete_steps(directory)
    return steps[-1] if steps else None


def read_topology(directory: str | Path,
                  step: int | None = None) -> dict | None:
    """Load a checkpoint's topology sidecar (None for pre-elastic saves
    — checkpoints written before the sidecar scheme, or an unreadable
    file; callers fall back to assuming the saved topology matches)."""
    base = Path(directory)
    if step is None:
        step = latest_step(base)
        if step is None:
            return None
    path = _topology_sidecar(base, step)
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def check_topology(saved: dict, live: dict, directory=None,
                   step: int | None = None,
                   elastic: bool = False) -> tuple[str, str]:
    """Validate a checkpoint's recorded topology against the live one.

    Returns ``(action, plan_line)`` from ``topology.elastic_plan``.
    Raises :class:`TopologyMismatchError` — ONE loud error naming the
    saved vs live topology, instead of the opaque Orbax sharding error
    a mismatched restore used to surface as — when the restore would
    need an elastic reshard (and ``elastic`` was not requested) or the
    transition is genuinely incompatible.
    """
    from tpu_hc_bench import topology as topo_mod

    action, plan = topo_mod.elastic_plan(saved, live)
    if action in ("ok", "noop"):
        return action, plan
    where = ""
    if directory is not None:
        where = f" under {directory}" + (
            f" (step {step})" if step is not None else "")
    head = (f"checkpoint topology mismatch{where}: saved "
            f"{topo_mod.describe_topology(saved)} vs live "
            f"{topo_mod.describe_topology(live)}")
    if action == "reshard" and not elastic:
        raise TopologyMismatchError(
            f"{head}; relaunch with --resume=elastic to reshape "
            f"({plan})")
    if action == "refuse":
        raise TopologyMismatchError(f"{head} — {plan}")
    return action, plan


def gc_checkpoints(directory: str | Path, keep: int,
                   print_fn=None, writer=None) -> list[int]:
    """--keep_checkpoints retention: keep the newest ``keep`` complete
    steps, delete the rest plus stale ``.tmp`` partial writes.  Returns
    the deleted step numbers.  Multi-process: process 0 only
    (single-writer, same shared filesystem the saves use).

    ``writer``: the run's :class:`AsyncCheckpointWriter`, if any.  GC
    barriers on it first — the ``.tmp`` reaping below would otherwise
    delete the very directory an in-flight overlapped save is still
    Orbax-writing into, turning that save's commit into a corrupt or
    failed checkpoint.  (The writer's OWN retention pass runs on the
    writer thread after its commit and must NOT pass itself — waiting
    on your own thread is a deadlock.)

    Sentinel-less final-name step dirs are deliberately LEFT ALONE:
    they are either crashed renames (rare, small) or checkpoints
    written before the sentinel scheme — deleting a legacy checkpoint
    as "debris" would be data loss (adopt one instead, see
    ``complete_steps``).
    """
    if keep <= 0:
        return []
    if writer is not None:
        writer.wait()
    if jax.process_count() > 1 and jax.process_index() != 0:
        return []
    base = Path(directory)
    steps = complete_steps(base)
    doomed = steps[:-keep]
    for step in doomed:
        # sentinel first: a crash mid-delete must not leave a sentinel
        # pointing at a half-deleted directory
        _marker(base, step).unlink(missing_ok=True)
        _topology_sidecar(base, step).unlink(missing_ok=True)
        shutil.rmtree(_step_dir(base, step), ignore_errors=True)
    for p in base.glob("step_*.tmp"):
        shutil.rmtree(p, ignore_errors=True)
    if doomed and print_fn is not None:
        print_fn(f"checkpoint GC: removed step(s) "
                 f"{', '.join(str(s) for s in doomed)} "
                 f"(--keep_checkpoints={keep})")
    return doomed


def fingerprint(tree) -> str:
    """Order-deterministic digest of every array leaf's raw bytes.

    The driver prints it at emergency save and at restore, so a
    kill/resume round trip can assert bitwise-identical params from the
    two log lines alone.  Requires fully-addressable arrays
    (single-process or replicated state).
    """
    h = hashlib.blake2b(digest_size=10)
    for leaf in jax.tree.leaves(jax.device_get(tree)):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def restore(state: TrainState, directory: str | Path,
            step: int | None = None, sharded: bool = False,
            expect_topology: dict | None = None) -> TrainState:
    """Restore into an already-constructed (template) TrainState.

    ``state`` supplies the tree structure, dtypes, and the non-serializable
    ``apply_fn``/``tx``; arrays are replaced from the checkpoint.

    ``sharded=True``: ``state`` must already be PLACED on the mesh (its
    arrays carry shardings); Orbax restores each array with that
    sharding, every process reading only the shards it addresses —
    the multi-host restore for model-sharded states.

    ``expect_topology``: the LIVE topology record.  When given and the
    checkpoint carries a sidecar, the two are validated up front — a
    restore that would need a reshard (or is incompatible) dies with
    one loud :class:`TopologyMismatchError` naming both topologies,
    not an opaque Orbax sharding/shape error mid-read.
    """
    base = Path(directory)
    if step is None:
        # falls back to the newest COMPLETE step: a crash mid-save left
        # an ignored .tmp / sentinel-less dir, not a broken "latest"
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no complete checkpoints under {base}")
    elif not _marker(base, step).exists():
        raise FileNotFoundError(
            f"checkpoint step {step} under {base} is incomplete (no "
            f"{_marker(base, step).name} sentinel — crashed save?); "
            f"complete steps: {complete_steps(base) or 'none'}")
    if expect_topology is not None:
        saved_topo = read_topology(base, step)
        if saved_topo is not None:
            check_topology(saved_topo, expect_topology, base, step)
    pull = (lambda t: t) if sharded else jax.device_get
    template = {
        "step": jax.device_get(state.step),
        "params": pull(state.params),
        "batch_stats": pull(state.batch_stats),
        "opt_state": pull(state.opt_state),
    }
    restore_args = None
    if sharded:
        def as_restore_args(x):
            return ocp.ArrayRestoreArgs(sharding=x.sharding,
                                        global_shape=x.shape,
                                        dtype=x.dtype)
        restore_args = {
            k: (ocp.RestoreArgs() if k == "step"
                else jax.tree.map(as_restore_args, template[k]))
            for k in template
        }
    ckptr = ocp.PyTreeCheckpointer()
    with timeline_mod.span("ckpt_restore", step=int(step)):
        payload = ckptr.restore(_step_dir(base, step).resolve(),
                                item=template, restore_args=restore_args)
    return state.replace(
        step=jax.numpy.asarray(payload["step"], dtype=jax.numpy.int32),
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
    )


def restore_elastic(state: TrainState, directory: str | Path,
                    saved_topology: dict | None, live_world: int,
                    step: int | None = None) -> TrainState:
    """Restore a HOST-layout checkpoint saved under a different world
    size onto the live one (``--resume=elastic``).

    Replicated trees (psum/replicated arms) are world-size neutral on
    disk — the plain restore already reassembles them; the caller
    re-places onto the live mesh.  The zero1 arm's gathered optimizer
    state is stacked ``[N_saved, k]`` per leaf: the restore goes through
    an old-layout host template (``train.step.zero1_opt_template``) and
    the shards are then resplit to ``[live_world, k']``
    (``train.step.resplit_zero1_opt``) — strip the old per-leaf zero
    padding, re-pad for the new axis size — so ``place_zero1_state``
    onto the new mesh round-trips bitwise.  Multi-host sharded and
    pp-native layouts never reach here (``topology.elastic_plan``
    refuses or routes them elsewhere).
    """
    if (saved_topology or {}).get("variable_update") == "zero1":
        from tpu_hc_bench.train import step as step_mod

        n_old = int(saved_topology["world"])
        old_opt = step_mod.zero1_opt_template(state.params, state.tx, n_old)
        restored = restore(state.replace(opt_state=old_opt), directory,
                           step=step)
        new_opt = step_mod.resplit_zero1_opt(
            restored.opt_state, state.params, state.tx, n_old,
            int(live_world))
        return restored.replace(opt_state=new_opt)
    return restore(state, directory, step=step)


def save_pp(params, opt_state, step: int, directory: str | Path,
            topology: dict | None = None) -> Path:
    """Multi-host PP checkpoint: the PP-NATIVE stacked layout, sharded.

    The DP<->PP checkpoint interchange (pipeline.pp_state_from_train_state)
    needs fully addressable arrays, which a multi-host pipe-sharded trunk
    is not — so multi-host PP saves the state AS IT IS SHARDED: the
    ``[L, ...]`` stacked trunk's LIVE jax.Arrays go straight to Orbax and
    every process writes only its addressable shards (round-4 closure of
    the driver's multi-host-PP --train_dir rejection).  Layout:
    ``<dir>/step_<n>/{pp_params,opt_state}``.  NOT interchangeable with
    the DP-layout checkpoints `save` writes (different tree: ``trunk`` vs
    ``layer_i``; a cross-restore fails loudly on structure mismatch) —
    but the stacked GLOBAL shapes are pipe-degree independent, so a
    PP-native checkpoint restores under any pipe degree whose mesh can
    place it.  ALL processes must call (Orbax barriers internally).
    ``opt_state=None`` saves params only.
    """
    base = Path(directory)
    base.mkdir(parents=True, exist_ok=True)
    tmp = base / (_step_dir(base, int(step)).name + ".tmp")
    stale_id = _marker_id(_marker(base, int(step)))
    with timeline_mod.span("ckpt_write", step=int(step)):
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save((tmp / "pp_params").resolve(), params, force=True)
        if opt_state is not None:
            ckptr.save((tmp / "opt_state").resolve(), opt_state,
                       force=True)
        return _commit_step_dir(base, int(step), tmp, stale_id,
                                topology=topology)


def restore_pp(params, opt_state, directory: str | Path,
               step: int | None = None):
    """Restore a PP-native checkpoint into PLACED templates.

    ``params``/``opt_state`` must already be placed on the mesh (their
    arrays carry the pipe/model shardings); each array restores with its
    committed sharding, every process reading only the shards it
    addresses.  ``opt_state=None`` restores params only (forward-only
    eval never places the momentum trace).  Returns
    ``(params, opt_state, step)``.
    """
    base = Path(directory)
    if step is None:
        step = latest_step(base)    # newest COMPLETE step (see restore)
        if step is None:
            raise FileNotFoundError(f"no complete checkpoints under {base}")
    elif not _marker(base, step).exists():
        raise FileNotFoundError(
            f"checkpoint step {step} under {base} is incomplete (no "
            f"{_marker(base, step).name} sentinel — crashed save?); "
            f"complete steps: {complete_steps(base) or 'none'}")
    path = _step_dir(base, step)

    def args_of(tree):
        return jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding,
                                           global_shape=x.shape,
                                           dtype=x.dtype), tree)

    ckptr = ocp.PyTreeCheckpointer()
    with timeline_mod.span("ckpt_restore", step=int(step)):
        params = ckptr.restore((path / "pp_params").resolve(), item=params,
                               restore_args=args_of(params))
        if opt_state is not None:
            opt_state = ckptr.restore((path / "opt_state").resolve(),
                                      item=opt_state,
                                      restore_args=args_of(opt_state))
    return params, opt_state, step
