"""Per-chip peak-FLOPs table for MFU accounting.

The reference never computes MFU (its metric is raw images/sec); the
BASELINE.json north star for this repo is ">=60% MFU on v5e", so the driver
needs peak numbers.  Figures are the public per-chip peak dense-matmul
rates (bf16 / fp32-equivalent) for each TPU generation; CPU gets a nominal
figure so MFU stays defined (if meaningless) on the test mesh.
"""

from __future__ import annotations

import jax

# (bf16_peak_flops, fp32_peak_flops) per chip
_PEAKS: dict[str, tuple[float, float]] = {
    "v5 lite": (394e12, 197e12),   # v5e: 394 TFLOPs int8/bf16-class MXU,
                                   # 197 TFLOPs bf16 — use (197, 98) conservatively
    "v5litepod": (197e12, 98e12),
    "v5e": (197e12, 98e12),
    "v5p": (459e12, 229e12),
    "v4": (275e12, 137e12),
    "v3": (123e12, 61e12),
    "v2": (45e12, 22e12),
    "v6": (918e12, 459e12),        # v6e (Trillium)
    "cpu": (1e11, 5e10),           # nominal, test-mesh only
}
# v5e correction: bf16 peak is 197 TFLOPs/chip; keep the conservative row.
_PEAKS["v5 lite"] = (197e12, 98e12)


def peak_flops(device: jax.Device | None = None, dtype: str = "bfloat16") -> float:
    """Best-effort peak FLOPs/s for one chip of this device kind."""
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, (bf16, f32) in _PEAKS.items():
        if key in kind:
            return bf16 if dtype == "bfloat16" else f32
    return _PEAKS["cpu"][0 if dtype == "bfloat16" else 1]


def device_kind() -> str:
    return jax.devices()[0].device_kind


def ici_topology_lines(devices=None) -> list[str]:
    """Live fabric introspection for the banner — the operator's ground
    truth before a run, playing the role of the reference's sysfs PKEY
    read + UCX_NET_DEVICES pin (run-tf-sing-ucx-openmpi.sh:85-95).

    Reports the slice shape (chip-coordinate bounding box), per-host chip
    counts, and each local chip's ICI coordinates.  Degrades gracefully on
    devices without coords (CPU test meshes): reports kinds only.
    """
    devices = list(devices if devices is not None else jax.devices())
    lines = []
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is not None for c in coords):
        known = [c for c in coords if c is not None]
        dims = range(len(known[0]))
        shape = "x".join(
            str(max(c[i] for c in known) - min(c[i] for c in known) + 1)
            for i in dims)
        lines.append(
            f"ici: slice_shape={shape} chips={len(known)} "
            f"kind={devices[0].device_kind}")
        per_host: dict[int, list] = {}
        for d, c in zip(devices, coords):
            per_host.setdefault(d.process_index, []).append(
                (d.id, c, getattr(d, "core_on_chip", 0)))
        for host in sorted(per_host):
            chips = " ".join(
                f"d{did}@{','.join(map(str, c))}" if c is not None
                else f"d{did}" for did, c, _ in per_host[host])
            lines.append(f"ici: host{host}: {chips}")
    else:
        lines.append(
            f"ici: no chip coordinates exposed ({devices[0].device_kind} "
            f"x{len(devices)}) — virtual/CPU mesh")
    return lines
