"""Per-chip peak-FLOPs table for MFU accounting.

The reference never computes MFU (its metric is raw images/sec); the
BASELINE.json north star for this repo is ">=60% MFU on v5e", so the driver
needs peak numbers.  Figures are the public per-chip peak dense-matmul
rates (bf16 / fp32-equivalent) for each TPU generation; CPU gets a nominal
figure so MFU stays defined (if meaningless) on the test mesh.
"""

from __future__ import annotations

import jax

# (bf16_peak_flops, fp32_peak_flops) per chip
_PEAKS: dict[str, tuple[float, float]] = {
    "v5 lite": (394e12, 197e12),   # v5e: 394 TFLOPs int8/bf16-class MXU,
                                   # 197 TFLOPs bf16 — use (197, 98) conservatively
    "v5litepod": (197e12, 98e12),
    "v5e": (197e12, 98e12),
    "v5p": (459e12, 229e12),
    "v4": (275e12, 137e12),
    "v3": (123e12, 61e12),
    "v2": (45e12, 22e12),
    "v6": (918e12, 459e12),        # v6e (Trillium)
    "cpu": (1e11, 5e10),           # nominal, test-mesh only
}
# v5e correction: bf16 peak is 197 TFLOPs/chip; keep the conservative row.
_PEAKS["v5 lite"] = (197e12, 98e12)


def peak_flops(device: jax.Device | None = None, dtype: str = "bfloat16") -> float:
    """Best-effort peak FLOPs/s for one chip of this device kind."""
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, (bf16, f32) in _PEAKS.items():
        if key in kind:
            return bf16 if dtype == "bfloat16" else f32
    return _PEAKS["cpu"][0 if dtype == "bfloat16" else 1]


def device_kind() -> str:
    return jax.devices()[0].device_kind
