"""Environment sanity report — the Singularity ``%runscript`` equivalent.

After every container build the reference runs ``singularity run`` which
prints and asserts the whole stack: OS, GCC, TF version, MKL linkage +
``IsMklEnabled()``, Horovod, OFED, MPI/UCX versions
(``tf-hvd-gcc-ompi-ucx-mlnx.def:45-55``, ``build-container.sh:29-30``) —
the reference's only integration test (SURVEY.md §4.1).

``python -m tpu_hc_bench.utils.sanity`` plays the same role for the TPU
stack: python/OS, jax/jaxlib/flax/optax versions, platform + device
inventory, a compiled-matmul smoke test asserting the XLA backend works
(the ``IsMklEnabled()`` analog: is the accelerator compiler actually in the
loop), a collective smoke test, and the env registry contents.  Exit code
is non-zero on any failed assertion so setup scripts can gate on it.
"""

from __future__ import annotations

import platform
import sys


def collect_report() -> tuple[list[str], list[str]]:
    """Returns (report_lines, failures)."""
    lines: list[str] = []
    failures: list[str] = []

    lines.append(f"host: {platform.node()} ({platform.platform()})")
    lines.append(f"python: {sys.version.split()[0]}")

    try:
        import jax
        import jaxlib

        lines.append(f"jax: {jax.__version__}  jaxlib: {jaxlib.__version__}")
    except Exception as e:
        failures.append(f"jax import failed: {e}")
        return lines, failures

    for mod in ("flax", "optax", "chex", "numpy"):
        try:
            m = __import__(mod)
            lines.append(f"{mod}: {m.__version__}")
        except Exception as e:
            failures.append(f"{mod} import failed: {e}")

    try:
        devs = jax.devices()
        lines.append(
            f"platform: {devs[0].platform}  device_kind: {devs[0].device_kind}"
        )
        lines.append(
            f"devices: {len(devs)} total, {jax.local_device_count()} local, "
            f"process {jax.process_index()}/{jax.process_count()}"
        )
    except Exception as e:
        failures.append(f"device discovery failed: {e}")
        return lines, failures

    # live fabric introspection (the ibv_devinfo / PKEY-read analog) —
    # informational: a failure here must not abort the report or flip the
    # exit code the setup scripts gate on
    try:
        from tpu_hc_bench.utils import hw

        lines.extend(hw.ici_topology_lines(devs))
    except Exception as e:
        lines.append(f"ici: topology introspection unavailable ({e})")

    # compiled-matmul smoke test: the IsMklEnabled() analog — proves the
    # XLA backend compiles and executes on the accelerator
    try:
        import jax.numpy as jnp

        x = jnp.ones((256, 256), jnp.bfloat16)
        y = jax.jit(lambda a: a @ a)(x)
        jax.block_until_ready(y)
        got = float(y[0, 0])
        if got != 256.0:
            failures.append(f"matmul smoke test wrong result: {got}")
        else:
            lines.append("xla matmul smoke test: ok (256x256 bf16)")
    except Exception as e:
        failures.append(f"xla matmul smoke test failed: {e}")

    # collective smoke test (single- or multi-device)
    try:
        from jax.sharding import PartitionSpec as P

        from tpu_hc_bench.topology import DATA_AXIS, build_mesh, discover_layout

        mesh = build_mesh(discover_layout())
        f = jax.jit(jax.shard_map(
            lambda v: jax.lax.psum(v, DATA_AXIS), mesh=mesh,
            in_specs=P(DATA_AXIS), out_specs=P(),
        ))
        import numpy as np

        n = mesh.devices.size
        out = f(jnp.arange(float(n)))
        expect = n * (n - 1) / 2
        if float(out[0]) != expect:
            failures.append(f"psum smoke test wrong result: {out}")
        else:
            lines.append(f"psum smoke test: ok over {n} device(s)")
    except Exception as e:
        failures.append(f"psum smoke test failed: {e}")

    # capability probes: the kernel/primitive surface the framework's
    # opt-in fast paths need (each degrades gracefully if absent, but the
    # report should say so up front)
    lines.append(
        "ragged_dot (grouped-matmul MoE): "
        + ("available" if hasattr(jax.lax, "ragged_dot") else "ABSENT")
    )
    try:
        from jax.experimental import pallas  # noqa: F401

        lines.append("pallas (flash attention, fused xent): importable")
    except Exception:
        # informational, not a failure: the default einsum-MoE and dense-
        # attention paths work without pallas
        lines.append("pallas (flash attention, fused xent): ABSENT")
    lines.append(
        "parallelism: dp (psum/GSPMD/host) + tp/ep (GSPMD model axis) "
        "+ pp (GPipe pipe axis) + sp (ring/ulysses/ulysses_flash seq axis)"
    )

    try:
        from tpu_hc_bench import envfile

        env = envfile.read()
        lines.append(f"env registry: {len(env)} entries at {envfile.DEFAULT_PATH}")
    except Exception as e:
        failures.append(f"env registry read failed: {e}")

    return lines, failures


def main() -> int:
    lines, failures = collect_report()
    print("=" * 60)
    print("tpu_hc_bench environment sanity report")
    print("=" * 60)
    for line in lines:
        print(f"  {line}")
    if failures:
        print("FAILURES:")
        for f in failures:
            print(f"  !! {f}")
        return 1
    print("all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
