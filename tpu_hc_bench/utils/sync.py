"""Reliable device synchronization for timing and draining.

``jax.block_until_ready`` is the documented way to wait for async
dispatch, but on tunneled platforms (the ``axon`` remote-TPU bridge in
particular) it is advisory: it — and ``jax.Array.is_ready()`` — report
completion early once the dispatch queue is deep (observed: truthful up
to ~30 outstanding executions, then unconditionally "ready", while a
value fetch of the same buffer still takes the full remaining execution
time).  The only trustworthy completion signal there is a value fetch,
so ``drain`` fetches: small leaves directly, large leaves through a
one-element dependent slice (forces execution without moving the
buffer).

Timing code should not call ``drain`` per step — a scalar fetch costs a
full tunnel round trip (~0.1s observed) — but pipeline fetches through a
background thread so the constant RTT cancels in arrival-time deltas
(see ``train.driver``).
"""

from __future__ import annotations

import jax

# Leaves at or below this size are fetched whole; larger ones through a
# 1-element slice so the drain never moves real buffers over the wire.
_SMALL_BYTES = 16384


def drain(tree):
    """Force true completion of every array in a pytree; returns the tree.

    Cost: one host round trip (plus tiny probe dispatches for large
    leaves).  Correct on every platform, including ones where
    block_until_ready/is_ready are advisory.
    """
    leaves = [x for x in jax.tree.leaves(tree) if isinstance(x, jax.Array)]
    if not leaves:
        return tree
    # cheap and sufficient on well-behaved platforms; advisory on axon
    jax.block_until_ready(leaves)
    probes = []
    for leaf in leaves:
        if not leaf.is_fully_addressable:
            # multi-process global array: values can't be fetched from one
            # process.  block_until_ready above is all we can do — fine in
            # practice, since the advisory-sync tunnel is single-process.
            continue
        if leaf.size and leaf.nbytes > _SMALL_BYTES:
            probes.append(leaf.ravel()[0])
        else:
            probes.append(leaf)
    if probes:
        jax.device_get(probes)
    return tree


def all_processes_any(flag: bool) -> bool:
    """Cross-host agreement: True iff ANY process passed True.

    The shared primitive for run-control decisions that must be
    unanimous — e.g. "stop and checkpoint now" on preemption, where a
    signal lands on one VM but a checkpoint written by half a mesh is
    garbage.  Single-process: a plain bool.  Multi-process: a tiny
    host-level allgather, so this is a COLLECTIVE — every process must
    call it at the same point (the driver calls it at sync-window
    boundaries, the same step everywhere).
    """
    import numpy as np

    if jax.process_count() <= 1:
        return bool(flag)
    from jax.experimental import multihost_utils

    votes = multihost_utils.process_allgather(
        np.asarray([1 if flag else 0], np.int32))
    return bool(np.max(votes))
